"""Adaptive (AQE-equivalent) shuffle reads and runtime replanning.

Reference: with AQE on, exchanges become query stages; after a stage's map
side runs, Spark replans reads using MapOutputStatistics and the plugin
supplies GpuCustomShuffleReaderExec for coalesced-partition reads
(GpuOverrides.scala:1874-1887, GpuTransitionOverrides.scala:51-94). The
reference v0.3 supports COALESCED reads (skewed-join splitting stayed on
CPU); this layer goes further and replans three ways once the map side
has materialized, because the block store makes the statistics exact:

1. **skew splitting** (OptimizeSkewedJoin analogue): partitions over the
   ``skewed_partitions`` cut are split into sub-reads while the other
   join side replicates the partition — the partition-aligned join
   contract survives because every (sub_i, replica) pair still covers
   exactly the co-partitioned key set.
2. **join-strategy switch**: ``AdaptiveShuffledJoinExec`` defers the
   shuffled-vs-broadcast (and hash-vs-dense probe) decision until the
   build-side exchange has materialized and its size is MEASURED, not
   estimated.
3. **stats-driven re-bucketing**: coalesced groups of 2+ map blocks are
   re-bucketed into one batch at the measured row count (the progcache
   serves the right ladder rung instead of padding each block), and
   measured exchange cardinalities feed ``estimate_footprint_bytes`` so
   out-of-core admission tightens as the workload runs.

Every replan is recorded as a replan event (``record_replan``) surfaced
through dispatch telemetry and the runner/bench JSON.
"""
from __future__ import annotations

import contextlib
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.execs.exchange import ShuffleExchangeExec
from spark_rapids_tpu.utils import lockorder

#: while active IN THIS THREAD, AdaptiveShuffleReaderExec.num_partitions
#: answers with the exchange's STATIC partition count instead of
#: computing groups. The groups computation materializes the whole map
#: stage (AQE's materialize-then-replan order — intended when the first
#: CONSUMER pulls at execute time), but planner rules also ask
#: num_partitions while building the plan, which used to run the entire
#: partial stage mid-planning — before downstream rules (fusion,
#: coalesce insertion) had rewritten the subtree. Spark's planner
#: likewise plans against static shuffle partitioning; only execution
#: replans adaptively. Thread-LOCAL: one session thread planning must
#: not suppress another thread's execute-time materialization.
_PLANNING = __import__("threading").local()


def planning_active() -> bool:
    return getattr(_PLANNING, "depth", 0) > 0


@contextlib.contextmanager
def planning_mode():
    _PLANNING.depth = getattr(_PLANNING, "depth", 0) + 1
    try:
        yield
    finally:
        _PLANNING.depth -= 1


# ---------------------------------------------------------------------------
# replan-event telemetry + measured-cardinality registry
# ---------------------------------------------------------------------------

#: {(rule, detail): count} — every physical-plan change made after
#: execution started. Process-global like parallel.spmd's fallback
#: counters; the runner/bench snapshot-delta them per run.
_replans: Dict[Tuple[str, str], int] = {}
#: {schema-names signature: max measured rows} — rule 3b's runtime
#: statistics, consumed by plan.optimizer.estimate_footprint_bytes via
#: the query service on later plans of the same shape.
_cardinalities: Dict[Tuple[str, ...], int] = {}
_replan_lock = lockorder.make_lock("execs.adaptive.replans")


def record_replan(rule: str, detail: str) -> None:
    """Count one replan event (rule in {skew_split, skew_salt,
    strategy_switch, rebucket})."""
    with _replan_lock:
        key = (rule, detail)
        _replans[key] = _replans.get(key, 0) + 1


def replan_snapshot() -> Dict[str, int]:
    with _replan_lock:
        return {f"{rule}: {detail}": n
                for (rule, detail), n in sorted(_replans.items())}


def replan_delta(before: Dict[str, int]) -> Dict[str, int]:
    """Positive event deltas since ``before`` (a replan_snapshot())."""
    now = replan_snapshot()
    return {k: v - before.get(k, 0) for k, v in now.items()
            if v - before.get(k, 0) > 0}


def record_cardinality(signature: Sequence[str], rows: int) -> None:
    """Record a MEASURED row count for plans whose node output matches
    ``signature`` (column names). Keeps the max seen — footprint
    admission wants the conservative bound."""
    sig = tuple(signature)
    with _replan_lock:
        if rows > _cardinalities.get(sig, -1):
            _cardinalities[sig] = rows


def cardinality_lookup(signature: Sequence[str]) -> Optional[int]:
    with _replan_lock:
        return _cardinalities.get(tuple(signature))


def plan_cardinality_rows(node) -> Optional[int]:
    """estimate_footprint_bytes ``runtime_rows`` hook: measured rows for
    a plan node, matched by output column names."""
    try:
        names = tuple(node.output_schema().names)
    except (AttributeError, TypeError, IndexError):
        return None  # schema-less node: no stats to serve
    return cardinality_lookup(names)


def _record_exchange_stats(exchange: ShuffleExchangeExec,
                          stats: "MapOutputStatistics") -> None:
    """Feed a materialized exchange's measured size into the
    cardinality registry (rows from capacity bytes / row width — an
    upper bound, which is the right direction for admission)."""
    try:
        names = tuple(exchange.schema.names)
        width = sum(t.byte_width + 1 for t in exchange.schema.types) or 1
    except (AttributeError, TypeError):
        return  # schema-less exchange: stats stay advisory-only
    record_cardinality(names, sum(stats.bytes_by_partition) // width)


class MapOutputStatistics:
    """Exact per-reduce-partition byte sizes of a materialized exchange
    (the MapOutputStatistics the AQE replan consumes)."""

    def __init__(self, bytes_by_partition: List[int]):
        self.bytes_by_partition = list(bytes_by_partition)

    @staticmethod
    def of(exchange: ShuffleExchangeExec) -> "MapOutputStatistics":
        exchange._materialize()
        return MapOutputStatistics(exchange.map_output_sizes())

    def skewed_partitions(self, factor: float = 5.0,
                          threshold: int = 256 << 20) -> List[int]:
        """Partitions larger than max(threshold, factor * median) — the
        OptimizeSkewedJoin detection rule."""
        sizes = sorted(self.bytes_by_partition)
        if not sizes:
            return []
        median = sizes[len(sizes) // 2]
        cut = max(threshold, factor * max(median, 1))
        return [i for i, s in enumerate(self.bytes_by_partition)
                if s > cut]


def coalesce_groups(stats: MapOutputStatistics, advisory_bytes: int,
                    min_partitions: int = 1) -> List[List[int]]:
    """Contiguous grouping targeting advisory_bytes per group (Spark's
    coalesceShufflePartitions algorithm: accumulate until the next
    partition would overflow a non-empty group)."""
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for p, size in enumerate(stats.bytes_by_partition):
        if cur and cur_bytes + size > advisory_bytes:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(p)
        cur_bytes += size
    if cur:
        groups.append(cur)
    # honor a minimum parallelism by splitting the largest groups at
    # their byte-balanced point — an index midpoint would recreate the
    # skew forced parallelism exists to avoid (one heavy half keeps the
    # straggler, the light half runs empty)
    while len(groups) < min_partitions:
        big = max(range(len(groups)),
                  key=lambda i: (len(groups[i]),
                                 sum(stats.bytes_by_partition[p]
                                     for p in groups[i])))
        g = groups[big]
        if len(g) <= 1:
            break
        sizes = [stats.bytes_by_partition[p] for p in g]
        total = sum(sizes)
        best_cut, best_imbalance, acc = 1, None, 0
        for j in range(1, len(g)):
            acc += sizes[j - 1]
            imbalance = abs(2 * acc - total)
            if best_imbalance is None or imbalance < best_imbalance:
                best_cut, best_imbalance = j, imbalance
        groups[big:big + 1] = [g[:best_cut], g[best_cut:]]
    return groups


#: A group entry is either a whole partition id or a sub-read
#: ``(pid, sub_index, sub_count)`` of a skew-split partition: the reader
#: serves every ``sub_count``-th map block of ``pid`` starting at
#: ``sub_index`` (block-granular round-robin — no device slicing, and
#: the union of the sub-reads is exactly the partition).
GroupEntry = Union[int, Tuple[int, int, int]]


def _split_count(size: int, advisory_bytes: int, max_splits: int) -> int:
    """Sub-reads for one skewed partition: target the advisory size but
    always split a DETECTED skew at least in two."""
    target = max(advisory_bytes, 1)
    return max(2, min(max_splits, -(-size // target)))


def skewed_group_pair(base_groups: List[List[int]],
                      left_stats: MapOutputStatistics,
                      right_stats: MapOutputStatistics,
                      kind: str, factor: float, threshold: int,
                      max_splits: int, advisory_bytes: int
                      ) -> Tuple[List[List[GroupEntry]],
                                 List[List[GroupEntry]]]:
    """Replan rule 1 on the host path: expand a shared coalesced group
    spec into two ALIGNED per-side specs where each skewed singleton
    group becomes sub-read x replica pairs.

    Splitting the STREAM (left) side while the build replicates is exact
    for every kind that never emits unmatched build rows (all kinds the
    planner routes here except ``full`` — each stream row lives in
    exactly one sub-read, so matched and unmatched emission both happen
    once). The BUILD side may additionally split for ``inner``, where
    neither side emits unmatched rows; both-sides-skewed takes the
    sub-read cross product."""
    if kind == "full":
        return base_groups, base_groups
    lhot = set(left_stats.skewed_partitions(factor, threshold))
    rhot = set(right_stats.skewed_partitions(factor, threshold)) \
        if kind == "inner" else set()
    lgroups: List[List[GroupEntry]] = []
    rgroups: List[List[GroupEntry]] = []
    for g in base_groups:
        # only singleton groups split: a partition over the skew cut is
        # alone in its group whenever advisory <= cut, and splitting a
        # merged group would tangle sub-reads of different partitions
        if len(g) != 1 or (g[0] not in lhot and g[0] not in rhot):
            lgroups.append(list(g))
            rgroups.append(list(g))
            continue
        p = g[0]
        nl = _split_count(left_stats.bytes_by_partition[p],
                          advisory_bytes, max_splits) if p in lhot else 1
        nr = _split_count(right_stats.bytes_by_partition[p],
                          advisory_bytes, max_splits) if p in rhot else 1
        for i in range(nl):
            for j in range(nr):
                lgroups.append([(p, i, nl)] if nl > 1 else [p])
                rgroups.append([(p, j, nr)] if nr > 1 else [p])
        side = "both" if (nl > 1 and nr > 1) else \
            ("stream" if nl > 1 else "build")
        record_replan("skew_split", f"{side} side, host path")
    return lgroups, rgroups


class AdaptiveShuffleReaderExec(TpuExec):
    """Serves coalesced partition groups of a materialized exchange
    (GpuCustomShuffleReaderExec analogue). ``groups_provider`` defers the
    statistics read until first access — the map stage runs when the
    first consumer pulls, exactly AQE's materialize-then-replan order.

    ``rebucket_bytes`` (replan rule 3a, set only on join-paired readers)
    re-buckets a group of 2+ map blocks whose measured bytes fit the
    limit into ONE batch at the measured row count: the progcache then
    serves the right ladder rung instead of padding every small block to
    its own bucket. Value-exact and order-preserving — concatenation in
    group order is the same row order the consumer would have seen."""

    def __init__(self, exchange: ShuffleExchangeExec,
                 advisory_bytes: int,
                 groups_provider=None,
                 rebucket_bytes: int = 0):
        super().__init__([exchange], exchange.schema)
        self.advisory_bytes = advisory_bytes
        self.rebucket_bytes = rebucket_bytes
        self._groups_provider = groups_provider
        self._groups: Optional[List[List[GroupEntry]]] = None

    @property
    def exchange(self) -> ShuffleExchangeExec:
        return self.children[0]

    # group providers are closures over live exchange objects; shipping
    # inside a cluster task closure resolves groups first (the cluster
    # runtime's task_tree forces self.groups before pickling) and drops
    # the provider — the worker reads the frozen spec
    def __getstate__(self):
        state = dict(self.__dict__)
        state["_groups_provider"] = None
        return state

    @property
    def groups(self) -> List[List[GroupEntry]]:
        if self._groups is None:
            if self._groups_provider is not None:
                self._groups = self._groups_provider()
            else:
                stats = MapOutputStatistics.of(self.exchange)
                self._groups = coalesce_groups(stats, self.advisory_bytes)
        return self._groups

    @property
    def num_partitions(self) -> int:
        if self._groups is None and planning_active():
            return self.exchange.num_out_partitions
        return len(self.groups)

    @property
    def coalesce_after(self):
        return self.exchange.coalesce_after

    def _entry_batches(self, entries: List[GroupEntry]
                       ) -> Iterator[ColumnarBatch]:
        for e in entries:
            if isinstance(e, tuple):
                p, sub_i, sub_n = e
                for bi, b in enumerate(self.exchange.execute(p)):
                    if bi % sub_n == sub_i:
                        yield b
            else:
                yield from self.exchange.execute(e)

    def _group_bytes(self, entries: List[GroupEntry]) -> int:
        sizes = self.exchange.map_output_sizes()
        total = 0
        for e in entries:
            if isinstance(e, tuple):
                p, _sub_i, sub_n = e
                total += sizes[p] // sub_n
            else:
                total += sizes[e]
        return total

    def _serve_rebucketed(self, entries: List[GroupEntry]
                          ) -> Iterator[ColumnarBatch]:
        from contextlib import ExitStack

        from spark_rapids_tpu.memory import priorities
        from spark_rapids_tpu.memory.retry import with_retry_no_split
        from spark_rapids_tpu.memory.spillable import SpillableBatch
        from spark_rapids_tpu.ops.concat import concat_batches

        staged: List[SpillableBatch] = []
        for b in self._entry_batches(entries):
            if b.realized_num_rows() == 0:
                continue
            staged.append(SpillableBatch(
                b, priorities.INPUT_FROM_SHUFFLE_PRIORITY))
        if not staged:
            yield ColumnarBatch.empty(self.schema)
            return
        if len(staged) == 1:
            with staged[0].acquired() as b:
                yield b
            staged[0].close()
            return
        with ExitStack() as stack:
            parts = [stack.enter_context(sb.acquired()) for sb in staged]
            merged = with_retry_no_split(
                lambda: concat_batches(parts),
                tag="adaptive.rebucket.concat")
        for sb in staged:
            sb.close()
        record_replan("rebucket", "group concat at measured rows")
        yield merged

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            entries = self.groups[partition]
            if self.rebucket_bytes and \
                    self._group_bytes(entries) <= self.rebucket_bytes:
                yield from self._serve_rebucketed(entries)
                return
            empty = True
            for b in self._entry_batches(entries):
                if b.realized_num_rows() == 0:
                    continue
                empty = False
                yield b
            if empty:
                yield ColumnarBatch.empty(self.schema)
        return timed(self, it())


def paired_adaptive_readers(left: ShuffleExchangeExec,
                            right: ShuffleExchangeExec,
                            advisory_bytes: int,
                            join_kind: Optional[str] = None,
                            skew: Optional[tuple] = None,
                            rebucket_bytes: int = 0
                            ) -> "tuple[TpuExec, TpuExec]":
    """One shared group spec for a join's two shuffles, computed lazily
    from the summed per-partition sizes so the partition-aligned join
    contract survives coalescing. With ``skew`` (a
    parallel.spmd.SkewSpec) and a splittable ``join_kind``, skewed
    singleton groups expand into aligned sub-read x replica pairs
    (replan rule 1)."""
    assert left.num_out_partitions == right.num_out_partitions
    cache: List[Optional[tuple]] = [None]
    readers: List[AdaptiveShuffleReaderExec] = []

    def resolve():
        # read through the READERS' current children, not the captured
        # exchanges: a post-planning pass (cluster mode) may swap the
        # exchange object underneath, and stats must come from the one
        # that actually materializes
        if cache[0] is None:
            ls = MapOutputStatistics.of(readers[0].exchange)
            rs = MapOutputStatistics.of(readers[1].exchange)
            _record_exchange_stats(readers[0].exchange, ls)
            _record_exchange_stats(readers[1].exchange, rs)
            combined = MapOutputStatistics(
                [a + b for a, b in zip(ls.bytes_by_partition,
                                       rs.bytes_by_partition)])
            base = coalesce_groups(combined, advisory_bytes)
            if skew is not None and join_kind is not None:
                cache[0] = skewed_group_pair(
                    base, ls, rs, join_kind, skew.factor, skew.threshold,
                    skew.max_splits, advisory_bytes)
            else:
                cache[0] = (base, base)
        return cache[0]

    readers.append(AdaptiveShuffleReaderExec(
        left, advisory_bytes, lambda: resolve()[0],
        rebucket_bytes=rebucket_bytes))
    readers.append(AdaptiveShuffleReaderExec(
        right, advisory_bytes, lambda: resolve()[1],
        rebucket_bytes=rebucket_bytes))
    return readers[0], readers[1]


class AdaptiveShuffledJoinExec(TpuExec):
    """Replan rule 2: a shuffled equi-join whose final strategy is
    decided at EXECUTE time from the materialized build-side exchange.

    The planner routes a would-be ShuffledHashJoinExec here when AQE is
    on; the first consumer pull materializes the build side's map stage
    (the stage boundary AQE replans at), then:

    - measured build bytes <= autoBroadcastJoinThreshold: re-plan as a
      broadcast join, reusing the build blocks through a whole-exchange
      reader and SKIPPING the stream-side shuffle entirely (the stream
      exchange's child feeds the probe directly, keeping its map-side
      partitioning) — the mis-estimated case the static planner cannot
      catch because scan statistics don't see filter selectivity;
    - otherwise: shuffled hash join over skew-aware aligned adaptive
      readers, with the dense-probe hint attached so joins.HashJoinExec
      can upgrade hash->dense per partition from the measured key range.

    Decision and children swap happen under the ``execs.adaptive.decide``
    barrier (planBarrier group — deciding materializes child exchanges,
    and the decision itself may run under an outer exchange's
    materialize)."""

    def __init__(self, kind: str, left: ShuffleExchangeExec,
                 right: ShuffleExchangeExec, left_keys: List[int],
                 right_keys: List[int], schema, condition=None,
                 conf=None):
        super().__init__([left, right], schema)
        self.kind = kind
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.condition = condition
        self.conf = conf
        self._inner: Optional[TpuExec] = None
        self._decide_lock = lockorder.make_lock("execs.adaptive.decide")

    def __getstate__(self):
        # cluster task closures resolve the decision first (like the
        # reader freezing its groups); the lock stays behind
        state = dict(self.__dict__)
        state.pop("_decide_lock", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._decide_lock = lockorder.make_lock("execs.adaptive.decide")

    @property
    def num_partitions(self) -> int:
        if self._inner is None and planning_active():
            return self.children[0].num_out_partitions
        return self._decide().num_partitions

    @property
    def children_coalesce_goal(self):
        return [None] * len(self.children)

    def _label_subtree(self, node: TpuExec) -> None:
        """Stage-label runtime-built nodes with this exec's own label so
        their dispatches don't surface as <unstaged> in the telemetry
        (cut_stages only saw the pre-decision tree)."""
        if getattr(node, "_stage_label", None) is None:
            node._stage_label = getattr(self, "_stage_label", None)
            for c in node.children:
                self._label_subtree(c)

    def _decide(self) -> TpuExec:
        if self._inner is not None:
            return self._inner
        with self._decide_lock:
            if self._inner is None:
                inner = self._plan_runtime()
                self._label_subtree(inner)
                self._inner = inner
                # downstream walkers (metrics, tree_string, plan
                # introspection) see the decided subtree
                self.children = [inner]
        return self._inner

    def _plan_runtime(self) -> TpuExec:
        from spark_rapids_tpu import config as cfg
        from spark_rapids_tpu.execs import joins
        from spark_rapids_tpu.parallel import spmd

        conf = self.conf
        lex, rex = self.children
        advisory = conf.get(cfg.ADVISORY_PARTITION_SIZE)
        rs = MapOutputStatistics.of(rex)
        _record_exchange_stats(rex, rs)
        build_bytes = sum(rs.bytes_by_partition)
        thr = conf.get(cfg.AUTO_BROADCAST_THRESHOLD)
        if (conf.get(cfg.ADAPTIVE_STRATEGY_SWITCH) and thr > 0
                and self.kind != "full" and build_bytes <= thr
                and type(lex) is ShuffleExchangeExec):
            return self._broadcast_plan(lex, rex, advisory)
        skew = spmd.adaptive_skew_spec(conf)
        rebucket = advisory if conf.get(cfg.ADAPTIVE_REBUCKET) else 0
        lr, rr = paired_adaptive_readers(
            lex, rex, advisory, join_kind=self.kind, skew=skew,
            rebucket_bytes=rebucket)
        join = joins.ShuffledHashJoinExec(
            self.kind, lr, rr, self.left_keys, self.right_keys,
            self.schema, self.condition, conf)
        if conf.get(cfg.ADAPTIVE_DENSE_JOIN):
            join._dense_spec = (conf.get(cfg.ADAPTIVE_DENSE_MAX_SPAN),
                                conf.get(cfg.ADAPTIVE_DENSE_MIN_DENSITY),
                                conf.get(cfg.ADAPTIVE_DENSE_MIN_ROWS))
        return join

    def _broadcast_plan(self, lex: ShuffleExchangeExec,
                        rex: ShuffleExchangeExec,
                        advisory: int) -> TpuExec:
        from spark_rapids_tpu.execs import joins
        from spark_rapids_tpu.execs.exchange import BroadcastExchangeExec
        from spark_rapids_tpu.plan.overrides import _ReplayExec

        # the stream side never shuffles: its exchange is abandoned
        # unmaterialized and the probe streams the map-side child with
        # its original partitioning (broadcast joins preserve stream
        # partitioning, so no contract changes)
        stream = lex.children[0]
        # the build blocks are already device-resident — serve ALL
        # partitions as one reader partition feeding the broadcast
        all_parts = [list(range(rex.num_out_partitions))]
        reader = AdaptiveShuffleReaderExec(
            rex, advisory, groups_provider=lambda: all_parts)
        build = _ReplayExec(BroadcastExchangeExec(reader),
                            stream.num_partitions)
        record_replan("strategy_switch", "shuffled->broadcast")
        return joins.BroadcastHashJoinExec(
            self.kind, stream, build, self.left_keys, self.right_keys,
            self.schema, self.condition, self.conf)

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        return self._decide().execute(partition)
