"""Adaptive (AQE-equivalent) shuffle reads.

Reference: with AQE on, exchanges become query stages; after a stage's map
side runs, Spark replans reads using MapOutputStatistics and the plugin
supplies GpuCustomShuffleReaderExec for coalesced-partition reads
(GpuOverrides.scala:1874-1887, GpuTransitionOverrides.scala:51-94). The
reference v0.3 supports COALESCED reads (skewed-join splitting stayed on
CPU), and so does this exec.

Here the exchange exec already materializes map output into a block store,
so statistics are exact: the reader computes contiguous partition groups
targeting the advisory size and serves each group as one output
partition. For joins, BOTH sides must coalesce identically — build the
groups from the summed per-partition sizes and share the spec
(CoalesceShufflePartitions applies one spec per stage the same way).
"""
from __future__ import annotations

import contextlib
from typing import Iterator, List, Optional, Sequence

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.execs.exchange import ShuffleExchangeExec

#: while active IN THIS THREAD, AdaptiveShuffleReaderExec.num_partitions
#: answers with the exchange's STATIC partition count instead of
#: computing groups. The groups computation materializes the whole map
#: stage (AQE's materialize-then-replan order — intended when the first
#: CONSUMER pulls at execute time), but planner rules also ask
#: num_partitions while building the plan, which used to run the entire
#: partial stage mid-planning — before downstream rules (fusion,
#: coalesce insertion) had rewritten the subtree. Spark's planner
#: likewise plans against static shuffle partitioning; only execution
#: replans adaptively. Thread-LOCAL: one session thread planning must
#: not suppress another thread's execute-time materialization.
_PLANNING = __import__("threading").local()


def planning_active() -> bool:
    return getattr(_PLANNING, "depth", 0) > 0


@contextlib.contextmanager
def planning_mode():
    _PLANNING.depth = getattr(_PLANNING, "depth", 0) + 1
    try:
        yield
    finally:
        _PLANNING.depth -= 1


class MapOutputStatistics:
    """Exact per-reduce-partition byte sizes of a materialized exchange
    (the MapOutputStatistics the AQE replan consumes)."""

    def __init__(self, bytes_by_partition: List[int]):
        self.bytes_by_partition = list(bytes_by_partition)

    @staticmethod
    def of(exchange: ShuffleExchangeExec) -> "MapOutputStatistics":
        exchange._materialize()
        return MapOutputStatistics(exchange.map_output_sizes())

    def skewed_partitions(self, factor: float = 5.0,
                          threshold: int = 256 << 20) -> List[int]:
        """Partitions larger than max(threshold, factor * median) — the
        OptimizeSkewedJoin detection rule; surfaced as diagnostics (the
        reference keeps skew handling on CPU in v0.3)."""
        sizes = sorted(self.bytes_by_partition)
        if not sizes:
            return []
        median = sizes[len(sizes) // 2]
        cut = max(threshold, factor * max(median, 1))
        return [i for i, s in enumerate(self.bytes_by_partition)
                if s > cut]


def coalesce_groups(stats: MapOutputStatistics, advisory_bytes: int,
                    min_partitions: int = 1) -> List[List[int]]:
    """Contiguous grouping targeting advisory_bytes per group (Spark's
    coalesceShufflePartitions algorithm: accumulate until the next
    partition would overflow a non-empty group)."""
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for p, size in enumerate(stats.bytes_by_partition):
        if cur and cur_bytes + size > advisory_bytes:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(p)
        cur_bytes += size
    if cur:
        groups.append(cur)
    # honor a minimum parallelism by splitting the largest groups
    while len(groups) < min_partitions:
        big = max(range(len(groups)),
                  key=lambda i: (len(groups[i]),
                                 sum(stats.bytes_by_partition[p]
                                     for p in groups[i])))
        g = groups[big]
        if len(g) <= 1:
            break
        mid = len(g) // 2
        groups[big:big + 1] = [g[:mid], g[mid:]]
    return groups


class AdaptiveShuffleReaderExec(TpuExec):
    """Serves coalesced partition groups of a materialized exchange
    (GpuCustomShuffleReaderExec analogue). ``groups_provider`` defers the
    statistics read until first access — the map stage runs when the
    first consumer pulls, exactly AQE's materialize-then-replan order."""

    def __init__(self, exchange: ShuffleExchangeExec,
                 advisory_bytes: int,
                 groups_provider=None):
        super().__init__([exchange], exchange.schema)
        self.advisory_bytes = advisory_bytes
        self._groups_provider = groups_provider
        self._groups: Optional[List[List[int]]] = None

    @property
    def exchange(self) -> ShuffleExchangeExec:
        return self.children[0]

    # group providers are closures over live exchange objects; shipping
    # inside a cluster task closure resolves groups first (the cluster
    # runtime's task_tree forces self.groups before pickling) and drops
    # the provider — the worker reads the frozen spec
    def __getstate__(self):
        state = dict(self.__dict__)
        state["_groups_provider"] = None
        return state

    @property
    def groups(self) -> List[List[int]]:
        if self._groups is None:
            if self._groups_provider is not None:
                self._groups = self._groups_provider()
            else:
                stats = MapOutputStatistics.of(self.exchange)
                self._groups = coalesce_groups(stats, self.advisory_bytes)
        return self._groups

    @property
    def num_partitions(self) -> int:
        if self._groups is None and planning_active():
            return self.exchange.num_out_partitions
        return len(self.groups)

    @property
    def coalesce_after(self):
        return self.exchange.coalesce_after

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            empty = True
            for p in self.groups[partition]:
                for b in self.exchange.execute(p):
                    if b.realized_num_rows() == 0:
                        continue
                    empty = False
                    yield b
            if empty:
                yield ColumnarBatch.empty(self.schema)
        return timed(self, it())


def paired_adaptive_readers(left: ShuffleExchangeExec,
                            right: ShuffleExchangeExec,
                            advisory_bytes: int
                            ) -> "tuple[TpuExec, TpuExec]":
    """One shared group spec for a join's two shuffles, computed lazily
    from the summed per-partition sizes so the partition-aligned join
    contract survives coalescing."""
    assert left.num_out_partitions == right.num_out_partitions
    cache: List[Optional[List[List[int]]]] = [None]
    readers: List[AdaptiveShuffleReaderExec] = []

    def provider():
        # read through the READERS' current children, not the captured
        # exchanges: a post-planning pass (cluster mode) may swap the
        # exchange object underneath, and stats must come from the one
        # that actually materializes
        if cache[0] is None:
            ls = MapOutputStatistics.of(readers[0].exchange)
            rs = MapOutputStatistics.of(readers[1].exchange)
            combined = MapOutputStatistics(
                [a + b for a, b in zip(ls.bytes_by_partition,
                                       rs.bytes_by_partition)])
            cache[0] = coalesce_groups(combined, advisory_bytes)
        return cache[0]

    readers.append(AdaptiveShuffleReaderExec(left, advisory_bytes,
                                             provider))
    readers.append(AdaptiveShuffleReaderExec(right, advisory_bytes,
                                             provider))
    return readers[0], readers[1]
