"""Python (pandas) integration execs — SURVEY.md §2.12.

The reference streams Arrow batches to GPU-aware Python workers for
pandas UDFs (GpuArrowEvalPythonExec.scala: BatchQueue + GpuArrowPython
Runner) and gates the map/grouped variants behind default-off flags
(GpuOverrides.scala:1888-1907). In-process, the "worker" is a direct
call: device batch -> pandas frame -> user function -> re-upload. A
worker-slot semaphore mirrors PythonWorkerSemaphore (bounding concurrent
Python evaluation when partitions run in parallel threads).
"""
from __future__ import annotations

import functools
import threading
from spark_rapids_tpu.utils import lockorder
from typing import Callable, Iterator, Optional

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.execs import interop
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.plan.nodes import PlanNode
from spark_rapids_tpu.utils.tracing import TraceRange


def run_udf(conf, fn, *args):
    # lazy: the udf package pulls the CPU engine, which imports back
    # into this module for the pandas plan nodes (circular at top level)
    from spark_rapids_tpu.udf.pyworker import run_udf as _run

    return _run(conf, fn, *args)


class MapInPandasNode(PlanNode):
    """df.mapInPandas analogue: ``fn`` maps a pandas DataFrame (one batch)
    to a pandas DataFrame with ``schema``."""

    def __init__(self, fn: Callable, schema: Schema, child: PlanNode):
        super().__init__([child])
        self.fn = fn
        self._schema = schema

    def output_schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return f"MapInPandas[{getattr(self.fn, '__name__', 'fn')}]"


class PythonWorkerSemaphore:
    """Bounds concurrent in-flight Python evaluations
    (python/PythonWorkerSemaphore.scala:144)."""

    _sem: Optional[threading.Semaphore] = None
    _slots = 4
    _lock = lockorder.make_lock("execs.python.pool")

    @classmethod
    def acquire(cls):
        with cls._lock:
            if cls._sem is None:
                cls._sem = threading.Semaphore(cls._slots)
        cls._sem.acquire()

    @classmethod
    def release(cls):
        cls._sem.release()


def _pandas_to_host(df, schema: Schema):
    data = {}
    validity = {}
    for name, typ in zip(schema.names, schema.types):
        if name not in df.columns:
            raise ValueError(
                f"mapInPandas result missing column {name!r}")
        s = df[name]
        if typ is dt.STRING:
            vals = np.array(
                [None if v is None or (isinstance(v, float) and
                                       np.isnan(v)) else str(v)
                 for v in s], dtype=object)
            data[name] = vals
            validity[name] = np.array([v is not None for v in vals],
                                      dtype=bool)
        else:
            isna = s.isna().to_numpy(dtype=bool)
            filled = s.fillna(0).to_numpy()
            data[name] = filled.astype(typ.np_dtype)
            validity[name] = ~isna
    return data, validity


class MapInPandasExec(TpuExec):
    def __init__(self, node: MapInPandasNode, child: TpuExec,
                 conf=None):
        super().__init__([child], node.output_schema())
        self.node = node
        self.conf = conf

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        child_schema = self.node.children[0].output_schema()
        out_schema = self.schema

        def it():
            for b in self.children[0].execute(partition):
                if b.realized_num_rows() == 0:
                    continue
                PythonWorkerSemaphore.acquire()
                try:
                    with TraceRange("MapInPandasExec.python"):
                        pdf = b.to_pandas(child_schema)
                        out = run_udf(self.conf, self.node.fn, pdf)
                        data, validity = _pandas_to_host(out, out_schema)
                finally:
                    PythonWorkerSemaphore.release()
                yield interop.host_to_batch(data, validity, out_schema)
            yield ColumnarBatch.empty(out_schema)
        return timed(self, it())


class GroupedMapInPandasNode(PlanNode):
    """groupBy(keys).applyInPandas analogue (GpuFlatMapGroupsInPandasExec,
    §2.12): ``fn`` maps each group's pandas DataFrame to a DataFrame with
    ``schema``. Null keys form their own group (Spark semantics)."""

    def __init__(self, grouping_ordinals, fn: Callable, schema: Schema,
                 child: PlanNode):
        super().__init__([child])
        assert grouping_ordinals, "grouped map requires grouping keys"
        self.grouping_ordinals = list(grouping_ordinals)
        self.fn = fn
        self._schema = schema

    def output_schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return (f"GroupedMapInPandas[{getattr(self.fn, '__name__', 'fn')}"
                f", keys={self.grouping_ordinals}]")


def _apply_grouped(pdf, key_names, fn, out_schema: Schema):
    import pandas as pd

    outs = []
    for _, g in pdf.groupby(key_names, dropna=False, sort=False):
        r = fn(g.reset_index(drop=True))
        if len(r):
            outs.append(r)
    if outs:
        return pd.concat(outs, ignore_index=True)
    return pd.DataFrame({n: pd.Series([], dtype=object)
                         for n in out_schema.names})


class GroupedMapInPandasExec(TpuExec):
    """Consumes a hash-exchanged child (the planner co-partitions by the
    grouping keys, so each group lives wholly in one partition)."""

    def __init__(self, node: GroupedMapInPandasNode, child: TpuExec,
                 conf=None):
        super().__init__([child], node.output_schema())
        self.node = node
        self.conf = conf

    @property
    def children_coalesce_goal(self):
        from spark_rapids_tpu.execs.batching import RequireSingleBatch

        return [RequireSingleBatch]

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.execs.batching import drain_to_single_batch

        child_schema = self.node.children[0].output_schema()
        out_schema = self.schema
        key_names = [child_schema.names[o]
                     for o in self.node.grouping_ordinals]

        def it():
            b = drain_to_single_batch(
                self.children[0].execute(partition), child_schema)
            if b.realized_num_rows() == 0:
                yield ColumnarBatch.empty(out_schema)
                return
            PythonWorkerSemaphore.acquire()
            try:
                with TraceRange("GroupedMapInPandasExec.python"):
                    pdf = b.to_pandas(child_schema)
                    out = run_udf(self.conf, functools.partial(
                        _apply_grouped, key_names=key_names,
                        fn=self.node.fn, out_schema=out_schema), pdf)
                    data, validity = _pandas_to_host(out, out_schema)
            finally:
                PythonWorkerSemaphore.release()
            yield interop.host_to_batch(data, validity, out_schema)
        return timed(self, it())


class CoGroupedMapInPandasNode(PlanNode):
    """cogroup(left, right).applyInPandas analogue
    (GpuFlatMapCoGroupsInPandasExec, §2.12): ``fn`` maps the pair of
    per-key group frames to a result frame; keys present on either side
    produce a call (the missing side's frame is empty)."""

    def __init__(self, left: PlanNode, right: PlanNode,
                 left_ordinals, right_ordinals, fn: Callable,
                 schema: Schema):
        super().__init__([left, right])
        assert len(left_ordinals) == len(right_ordinals) > 0
        self.left_ordinals = list(left_ordinals)
        self.right_ordinals = list(right_ordinals)
        self.fn = fn
        self._schema = schema

    def output_schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return (f"CoGroupedMapInPandas["
                f"{getattr(self.fn, '__name__', 'fn')}]")


_NULL_KEY = object()  # canonical image for None/NaN grouping keys


def _canon_key(k):
    """Dict-safe group key: pandas hands out nan objects whose hash is
    identity-based, so NaN (and None) keys from the two sides would
    never match — canonicalize them to one sentinel."""
    t = k if isinstance(k, tuple) else (k,)
    return tuple(_NULL_KEY if v is None or v != v else v for v in t)


def _apply_cogrouped(lpdf, rpdf, lkeys, rkeys, fn, out_schema: Schema):
    import pandas as pd

    lgroups = {_canon_key(k): g.reset_index(drop=True)
               for k, g in lpdf.groupby(lkeys, dropna=False, sort=False)}
    rgroups = {_canon_key(k): g.reset_index(drop=True)
               for k, g in rpdf.groupby(rkeys, dropna=False, sort=False)}
    outs = []
    seen = list(lgroups) + [k for k in rgroups if k not in lgroups]

    def key_sort(k):
        return tuple((v is _NULL_KEY, str(v)) for v in k)

    for k in sorted(seen, key=key_sort):
        lg = lgroups.get(k, lpdf.iloc[0:0])
        rg = rgroups.get(k, rpdf.iloc[0:0])
        r = fn(lg, rg)
        if len(r):
            outs.append(r)
    if outs:
        return pd.concat(outs, ignore_index=True)
    return pd.DataFrame({n: pd.Series([], dtype=object)
                         for n in out_schema.names})


class CoGroupedMapInPandasExec(TpuExec):
    """Both children are hash-co-partitioned on their keys by the
    planner, so matching groups meet in the same partition."""

    def __init__(self, node: CoGroupedMapInPandasNode, left: TpuExec,
                 right: TpuExec, conf=None):
        super().__init__([left, right], node.output_schema())
        self.node = node
        self.conf = conf

    @property
    def children_coalesce_goal(self):
        from spark_rapids_tpu.execs.batching import RequireSingleBatch

        return [RequireSingleBatch, RequireSingleBatch]

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.execs.batching import drain_to_single_batch

        lschema = self.node.children[0].output_schema()
        rschema = self.node.children[1].output_schema()
        out_schema = self.schema
        lkeys = [lschema.names[o] for o in self.node.left_ordinals]
        rkeys = [rschema.names[o] for o in self.node.right_ordinals]

        def it():
            lb = drain_to_single_batch(
                self.children[0].execute(partition), lschema)
            rb = drain_to_single_batch(
                self.children[1].execute(partition), rschema)
            if lb.realized_num_rows() == 0 and \
                    rb.realized_num_rows() == 0:
                yield ColumnarBatch.empty(out_schema)
                return
            PythonWorkerSemaphore.acquire()
            try:
                with TraceRange("CoGroupedMapInPandasExec.python"):
                    out = run_udf(
                        self.conf, functools.partial(
                            _apply_cogrouped, lkeys=lkeys, rkeys=rkeys,
                            fn=self.node.fn, out_schema=out_schema),
                        lb.to_pandas(lschema), rb.to_pandas(rschema))
                    data, validity = _pandas_to_host(out, out_schema)
            finally:
                PythonWorkerSemaphore.release()
            yield interop.host_to_batch(data, validity, out_schema)
        return timed(self, it())


class WindowInPandasNode(PlanNode):
    """window-over pandas UDF analogue (GpuWindowInPandasExec, the shim
    exec of §2.12): ``fn`` receives one partition-group's pandas DataFrame
    sorted by ``order_specs`` and returns a sequence/Series of
    ``out_dtype`` values aligned 1:1 with the group's rows (the
    unbounded-window grouped-vectorized case Spark's WindowInPandas
    serves). Output = child columns + the new column; row identity is
    preserved (results map back to input row order)."""

    def __init__(self, partition_ordinals, order_specs, fn: Callable,
                 out_name: str, out_dtype, child: PlanNode):
        super().__init__([child])
        assert partition_ordinals, "window-in-pandas requires partitions"
        self.partition_ordinals = list(partition_ordinals)
        self.order_specs = list(order_specs)
        self.fn = fn
        self.out_name = out_name
        self.out_dtype = out_dtype

    def output_schema(self) -> Schema:
        s = self.children[0].output_schema()
        return Schema(list(s.names) + [self.out_name],
                      list(s.types) + [self.out_dtype])

    def describe(self) -> str:
        return (f"WindowInPandas[{getattr(self.fn, '__name__', 'fn')}, "
                f"part={self.partition_ordinals}]")


def _sort_group_by_specs(g, child_schema: Schema, order_specs):
    """Stable sort honoring per-key nulls_first/ascending (pandas
    na_position is global, so null ranks become explicit key columns)."""
    if not order_specs:
        return g
    work = g.copy()
    sort_cols = []
    ascending = []
    for i, s in enumerate(order_specs):
        name = child_schema.names[s.ordinal]
        rank_col = f"__nullrank_{i}"
        isna = work[name].isna()
        # ascending rank: NULLS FIRST -> null rank 0; LAST -> null rank 1
        work[rank_col] = (~isna).astype(int) if s.nulls_first \
            else isna.astype(int)
        sort_cols += [rank_col, name]
        ascending += [True, s.ascending]
    out = work.sort_values(sort_cols, ascending=ascending, kind="stable",
                           na_position="last")
    return out[g.columns]


def _apply_window_in_pandas(pdf, partition_ordinals, order_specs, fn,
                            out_name, child_schema: Schema):
    """Shared TPU/CPU body: group -> sort -> fn -> align back by index.
    Takes plain fields (not the plan node) so a worker process never
    deserializes the child plan subtree."""
    import pandas as pd

    key_names = [child_schema.names[o] for o in partition_ordinals]
    out = pd.Series([None] * len(pdf), index=pdf.index, dtype=object)
    for _, g in pdf.groupby(key_names, dropna=False, sort=False):
        g = _sort_group_by_specs(g, child_schema, order_specs)
        vals = fn(g.reset_index(drop=True))
        vals = list(vals)
        if len(vals) != len(g):
            raise ValueError(
                f"window fn returned {len(vals)} values for a "
                f"{len(g)}-row partition")
        out.loc[g.index] = vals
    result = pdf.copy()
    result[out_name] = out
    return result


class WindowInPandasExec(TpuExec):
    """Child is hash-co-partitioned on the partition keys by the planner
    (each window partition lives wholly in one task partition)."""

    def __init__(self, node: WindowInPandasNode, child: TpuExec,
                 conf=None):
        super().__init__([child], node.output_schema())
        self.node = node
        self.conf = conf

    @property
    def children_coalesce_goal(self):
        from spark_rapids_tpu.execs.batching import RequireSingleBatch

        return [RequireSingleBatch]

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.execs.batching import drain_to_single_batch

        child_schema = self.node.children[0].output_schema()
        out_schema = self.schema

        def it():
            b = drain_to_single_batch(
                self.children[0].execute(partition), child_schema)
            if b.realized_num_rows() == 0:
                yield ColumnarBatch.empty(out_schema)
                return
            PythonWorkerSemaphore.acquire()
            try:
                with TraceRange("WindowInPandasExec.python"):
                    pdf = b.to_pandas(child_schema)
                    out = run_udf(self.conf, functools.partial(
                        _apply_window_in_pandas,
                        partition_ordinals=self.node.partition_ordinals,
                        order_specs=self.node.order_specs,
                        fn=self.node.fn, out_name=self.node.out_name,
                        child_schema=child_schema), pdf)
                    data, validity = _pandas_to_host(out, out_schema)
            finally:
                PythonWorkerSemaphore.release()
            yield interop.host_to_batch(data, validity, out_schema)
        return timed(self, it())


def execute_window_in_pandas_cpu(node: WindowInPandasNode):
    from spark_rapids_tpu.cpu.engine import execute_cpu

    child = execute_cpu(node.children[0])
    child_schema = node.children[0].output_schema()
    out = _apply_window_in_pandas(
        child.to_pandas(), node.partition_ordinals, node.order_specs,
        node.fn, node.out_name, child_schema)
    return _cpu_frame_from_pandas(out, node.output_schema())


class ArrowEvalPythonNode(PlanNode):
    """Scalar pandas-UDF projection (GpuArrowEvalPythonExec,
    GpuArrowEvalPythonExec.scala:494): each udf is
    (fn, input_ordinals, out_name, out_dtype) where ``fn`` maps pandas
    Series positionally to a Series of results, evaluated per batch and
    APPENDED to the child columns (Spark's EvalPython output shape)."""

    def __init__(self, udfs, child: PlanNode):
        super().__init__([child])
        assert udfs
        self.udfs = list(udfs)

    def output_schema(self) -> Schema:
        s = self.children[0].output_schema()
        names = list(s.names) + [u[2] for u in self.udfs]
        types = list(s.types) + [u[3] for u in self.udfs]
        return Schema(names, types)

    def describe(self) -> str:
        return f"ArrowEvalPython[{len(self.udfs)} udfs]"


def _apply_scalar_udfs(pdf, udfs, child_schema: Schema):
    import pandas as pd

    out = pdf.copy()
    for fn, ordinals, name, _dtype in udfs:
        args = [pdf[child_schema.names[o]] for o in ordinals]
        r = pd.Series(fn(*args))
        if len(r) != len(pdf):
            raise ValueError(
                f"pandas UDF {name!r} returned {len(r)} rows for a "
                f"{len(pdf)}-row batch")
        out[name] = r.reset_index(drop=True).set_axis(out.index)
    return out


class ArrowEvalPythonExec(TpuExec):
    def __init__(self, node: ArrowEvalPythonNode, child: TpuExec,
                 conf=None):
        super().__init__([child], node.output_schema())
        self.node = node
        self.conf = conf

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        child_schema = self.node.children[0].output_schema()
        out_schema = self.schema

        def it():
            for b in self.children[0].execute(partition):
                if b.realized_num_rows() == 0:
                    continue
                PythonWorkerSemaphore.acquire()
                try:
                    with TraceRange("ArrowEvalPythonExec.python"):
                        pdf = b.to_pandas(child_schema)
                        out = run_udf(self.conf, functools.partial(
                            _apply_scalar_udfs, udfs=self.node.udfs,
                            child_schema=child_schema), pdf)
                        data, validity = _pandas_to_host(out, out_schema)
                finally:
                    PythonWorkerSemaphore.release()
                yield interop.host_to_batch(data, validity, out_schema)
            yield ColumnarBatch.empty(out_schema)
        return timed(self, it())


def execute_arrow_eval_python_cpu(node: ArrowEvalPythonNode):
    from spark_rapids_tpu.cpu.engine import execute_cpu

    child = execute_cpu(node.children[0])
    child_schema = node.children[0].output_schema()
    out = _apply_scalar_udfs(child.to_pandas(), node.udfs, child_schema)
    return _cpu_frame_from_pandas(out, node.output_schema())


class AggregateInPandasNode(PlanNode):
    """groupBy().agg(pandas_udf) analogue (GpuAggregateInPandasExec,
    §2.12): ``fn`` maps one group's pandas DataFrame to a single row —
    a tuple/list of the non-key output columns; output = keys + those."""

    def __init__(self, grouping_ordinals, fn: Callable, schema: Schema,
                 child: PlanNode):
        super().__init__([child])
        assert grouping_ordinals, "aggregate-in-pandas requires keys"
        self.grouping_ordinals = list(grouping_ordinals)
        self.fn = fn
        self._schema = schema

    def output_schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return (f"AggregateInPandas["
                f"{getattr(self.fn, '__name__', 'fn')}]")


def _apply_agg_in_pandas(pdf, grouping_ordinals, fn,
                         out_schema: Schema, child_schema: Schema):
    import pandas as pd

    key_names = [child_schema.names[o] for o in grouping_ordinals]
    rows = []
    for key, g in pdf.groupby(key_names, dropna=False, sort=False):
        key = key if isinstance(key, tuple) else (key,)
        vals = fn(g.reset_index(drop=True))
        if not isinstance(vals, (tuple, list)):
            vals = (vals,)
        rows.append(tuple(key) + tuple(vals))
    if rows:
        return pd.DataFrame(rows, columns=list(out_schema.names))
    return pd.DataFrame({n: pd.Series([], dtype=object)
                         for n in out_schema.names})


class AggregateInPandasExec(TpuExec):
    """Child hash-co-partitioned on the keys by the planner."""

    def __init__(self, node: AggregateInPandasNode, child: TpuExec,
                 conf=None):
        super().__init__([child], node.output_schema())
        self.node = node
        self.conf = conf

    @property
    def children_coalesce_goal(self):
        from spark_rapids_tpu.execs.batching import RequireSingleBatch

        return [RequireSingleBatch]

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.execs.batching import drain_to_single_batch

        child_schema = self.node.children[0].output_schema()
        out_schema = self.schema

        def it():
            b = drain_to_single_batch(
                self.children[0].execute(partition), child_schema)
            if b.realized_num_rows() == 0:
                yield ColumnarBatch.empty(out_schema)
                return
            PythonWorkerSemaphore.acquire()
            try:
                with TraceRange("AggregateInPandasExec.python"):
                    out = run_udf(
                        self.conf, functools.partial(
                            _apply_agg_in_pandas,
                            grouping_ordinals=self.node.grouping_ordinals,
                            fn=self.node.fn, out_schema=out_schema,
                            child_schema=child_schema),
                        b.to_pandas(child_schema))
                    data, validity = _pandas_to_host(out, out_schema)
            finally:
                PythonWorkerSemaphore.release()
            yield interop.host_to_batch(data, validity, out_schema)
        return timed(self, it())


def execute_agg_in_pandas_cpu(node: AggregateInPandasNode):
    from spark_rapids_tpu.cpu.engine import execute_cpu

    child = execute_cpu(node.children[0])
    child_schema = node.children[0].output_schema()
    out = _apply_agg_in_pandas(
        child.to_pandas(), node.grouping_ordinals, node.fn,
        node.output_schema(), child_schema)
    return _cpu_frame_from_pandas(out, node.output_schema())


def _cpu_frame_from_pandas(out, schema: Schema):
    """Shared pandas-result -> CpuFrame tail for the CPU-engine pandas
    execs."""
    from spark_rapids_tpu.cpu.engine import CpuFrame
    from spark_rapids_tpu.cpu.evaluator import CV

    data, validity = _pandas_to_host(out, schema)
    n = len(next(iter(data.values()))) if len(schema) else 0
    cols = [CV(t, data[nm], validity[nm])
            for nm, t in zip(schema.names, schema.types)]
    return CpuFrame(schema, cols, n)


def execute_cogrouped_map_cpu(node: CoGroupedMapInPandasNode):
    from spark_rapids_tpu.cpu.engine import execute_cpu

    left = execute_cpu(node.children[0])
    right = execute_cpu(node.children[1])
    lschema = node.children[0].output_schema()
    rschema = node.children[1].output_schema()
    schema = node.output_schema()
    out = _apply_cogrouped(
        left.to_pandas(), right.to_pandas(),
        [lschema.names[o] for o in node.left_ordinals],
        [rschema.names[o] for o in node.right_ordinals],
        node.fn, schema)
    return _cpu_frame_from_pandas(out, schema)


def execute_grouped_map_cpu(node: GroupedMapInPandasNode):
    from spark_rapids_tpu.cpu.engine import execute_cpu

    child = execute_cpu(node.children[0])
    schema = node.output_schema()
    child_schema = node.children[0].output_schema()
    key_names = [child_schema.names[o] for o in node.grouping_ordinals]
    out = _apply_grouped(child.to_pandas(), key_names, node.fn, schema)
    return _cpu_frame_from_pandas(out, schema)


def execute_map_in_pandas_cpu(node: MapInPandasNode):
    """CPU-engine implementation (oracle): same function applied to the
    whole child frame."""
    from spark_rapids_tpu.cpu.engine import execute_cpu

    child = execute_cpu(node.children[0])
    schema = node.output_schema()
    out = node.fn(child.to_pandas())
    return _cpu_frame_from_pandas(out, schema)
