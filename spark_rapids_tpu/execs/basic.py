"""Basic execs: scan, project, filter, range, limit, union, expand, and the
CPU-fallback bridge (reference basicPhysicalOperators.scala,
GpuExpandExec.scala, and the transition execs)."""
from __future__ import annotations

import math
from typing import Iterator, List, Optional

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.execs import interop
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.expressions.base import Expression
from spark_rapids_tpu.expressions.compiler import (CompiledFilter,
                                                   CompiledProjection)
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.plan.nodes import DataSource
from spark_rapids_tpu.utils.tracing import TraceRange


class ScanExec(TpuExec):
    """Host read -> sliced device uploads (GpuFileSourceScanExec +
    the semaphore acquire before first device touch, GpuSemaphore.scala:106).
    Rows per upload slice come from the batch-size config. File sources
    with multiple splits expose them as scan partitions (the reference's
    FilePartition -> task mapping).

    The read itself runs through the bounded-depth async scan pipeline
    (io/scanpipe.py): chunk-granular reads (row groups / stripes) are
    re-sliced to exact batch_rows boundaries, packed on an IO thread,
    and double-buffered through device_put — slice k+1's transfer is in
    flight while the caller computes on slice k. Prefetch depth,
    pruning, and spillable landing come from the source's
    ``rapids.tpu.io.scan.*`` conf; depth 0 is the synchronous
    byte-identical reference path."""

    #: planner-set (fused.py): hand packed uploads to the consuming
    #: fused chain undecoded; the chain inlines the decode in-program
    defer_decode = False

    def __init__(self, source: DataSource, schema: Schema,
                 batch_rows: int = 1 << 20, pack: bool = True):
        super().__init__([], schema)
        self.source = source
        self.batch_rows = batch_rows
        self.pack = pack

    @property
    def num_partitions(self) -> int:
        return self.source.num_splits()

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.io import scanpipe

        return timed(self, scanpipe.scan_iter(self, partition))


class DeviceBatchesExec(TpuExec):
    """Serves pre-existing device batches without any host round trip
    (the InternalColumnarRddConverter ingestion path)."""

    def __init__(self, source, schema: Schema):
        super().__init__([], schema)
        self.source = source

    @property
    def num_partitions(self) -> int:
        return max(len(self.source.batches), 1)

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            if not self.source.batches:
                yield ColumnarBatch.empty(self.schema)
                return
            yield self.source.batches[partition]
        return timed(self, it())


class ProjectExec(TpuExec):
    """One fused XLA computation per batch (GpuProjectExec,
    basicPhysicalOperators.scala:35-95)."""

    def __init__(self, exprs: List[Expression], child: TpuExec,
                 schema: Schema, conf=None):
        super().__init__([child], schema)
        self.projection = CompiledProjection(exprs, conf)

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.expressions.nondeterministic import TaskInfo

        def it():
            row_base = 0
            for b in self.children[0].execute(partition):
                ti = TaskInfo.make(partition, row_base)
                with TraceRange("ProjectExec"):
                    out = self.projection(b, task_info=ti)
                row_base += b.realized_num_rows()
                yield out
        return timed(self, it())


class FilterExec(TpuExec):
    """Mask + compact in one jitted kernel (GpuFilterExec,
    basicPhysicalOperators.scala:100-130)."""

    def __init__(self, condition: Expression, child: TpuExec, conf=None):
        super().__init__([child], child.schema)
        self.filter = CompiledFilter(condition, conf)

    def __getstate__(self):
        # the mesh layer may cache a compiled sharded filter step on
        # this exec (parallel/execs._apply_mesh_filter); it holds live
        # Device handles and must not ship in cluster task closures
        state = dict(self.__dict__)
        state.pop("_mesh_filter_step", None)
        return state

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.expressions.nondeterministic import TaskInfo

        def it():
            row_base = 0
            for b in self.children[0].execute(partition):
                ti = TaskInfo.make(partition, row_base)
                with TraceRange("FilterExec"):
                    out = self.filter(b, task_info=ti)
                # a filter keeps file provenance (Spark's
                # input_file_name still works below a filter)
                out.origin = b.origin
                row_base += b.realized_num_rows()
                yield out
        return timed(self, it())


class RangeExec(TpuExec):
    """Generates batches on device (GpuRangeExec)."""

    def __init__(self, start: int, end: int, step: int, schema: Schema,
                 batch_rows: int = 1 << 20):
        super().__init__([], schema)
        self.start, self.end, self.step = start, end, step
        self.batch_rows = batch_rows

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            total = max(0, math.ceil((self.end - self.start) / self.step))
            if total == 0:
                yield ColumnarBatch.empty(self.schema)
                return
            for off in range(0, total, self.batch_rows):
                cnt = min(self.batch_rows, total - off)
                lo = self.start + off * self.step
                vals = np.arange(
                    lo, lo + cnt * self.step, self.step, dtype=np.int64)
                yield ColumnarBatch(
                    [Column.from_numpy(vals, dtype=dt.INT64)], cnt)
        return timed(self, it())


class LocalLimitExec(TpuExec):
    """Slices batches until n rows have been emitted (per partition)."""

    def __init__(self, n: int, child: TpuExec):
        super().__init__([child], child.schema)
        self.n = n

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            remaining = self.n
            for b in self.children[0].execute(partition):
                if remaining <= 0:
                    break
                rows = b.realized_num_rows()
                if rows <= remaining:
                    remaining -= rows
                    yield b
                else:
                    yield b.slice(0, remaining)
                    remaining = 0
        return timed(self, it())


class UnionExec(TpuExec):
    """Concatenates children lazily (GpuOverrides.scala:1777-1833 union).
    Child partition counts may differ; partitions are concatenated
    child-major."""

    def __init__(self, children: List[TpuExec], schema: Schema):
        super().__init__(children, schema)

    @property
    def num_partitions(self) -> int:
        return sum(c.num_partitions for c in self.children)

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            p = partition
            for c in self.children:
                if p < c.num_partitions:
                    yield from c.execute(p)
                    return
                p -= c.num_partitions
            raise IndexError(partition)
        return timed(self, it())


class ExpandExec(TpuExec):
    """Per input batch, evaluate each projection then interleave row-major
    — Spark's ExpandExec/explode emission order, one output row per
    (input row, projection) pair (GpuExpandExec.scala)."""

    def __init__(self, projections: List[List[Expression]], child: TpuExec,
                 schema: Schema, conf=None):
        super().__init__([child], schema)
        self.projections = [CompiledProjection(p, conf)
                            for p in projections]

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.ops.concat import interleave_batches

        def it():
            for b in self.children[0].execute(partition):
                parts = [proj(b) for proj in self.projections]
                with TraceRange("ExpandExec.interleave"):
                    yield interleave_batches(parts)
        return timed(self, it())


class CoalescePartitionsExec(TpuExec):
    """Maps n output partitions onto contiguous groups of child
    partitions — no data movement beyond sequential reads."""

    def __init__(self, num_partitions: int, child: TpuExec):
        super().__init__([child], child.schema)
        self._n = num_partitions

    @property
    def num_partitions(self) -> int:
        return min(self._n, max(self.children[0].num_partitions, 1))

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            child_n = self.children[0].num_partitions
            n = self.num_partitions
            per = -(-child_n // n)
            lo = partition * per
            hi = min(lo + per, child_n)
            empty = True
            for p in range(lo, hi):
                for b in self.children[0].execute(p):
                    if b.realized_num_rows() == 0:
                        continue
                    empty = False
                    yield b
            if empty:
                yield ColumnarBatch.empty(self.schema)
        return timed(self, it())


class CpuFallbackExec(TpuExec):
    """Executes a plan subtree on the CPU engine and uploads the result —
    the planner inserts this around nodes that can't go on TPU, with the
    tag reasons recorded (the reference's convertIfNeeded keeps such
    subtrees as CPU Spark plans, RapidsMeta.scala:600-615)."""

    def __init__(self, plan_node, schema: Schema, reasons: List[str],
                 tpu_children: Optional[List[TpuExec]] = None,
                 batch_rows: int = 1 << 20):
        super().__init__(tpu_children or [], schema)
        self.plan_node = plan_node
        self.reasons = reasons
        self.batch_rows = batch_rows

    @property
    def num_partitions(self) -> int:
        return 1

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.cpu.engine import execute_cpu

        def it():
            frame = execute_cpu(self.plan_node)
            n = frame.num_rows
            if n == 0:
                yield interop.frame_to_batch(frame)
                return
            for start in range(0, n, self.batch_rows):
                end = min(start + self.batch_rows, n)
                idx = np.arange(start, end)
                yield interop.frame_to_batch(frame.take(idx))
        return timed(self, it())
