"""TPU exec layer (reference L4, GpuExec.scala): physical operators that
stream ColumnarBatches through jit-compiled kernels. Each exec declares its
batching contract via CoalesceGoal (GpuExec.scala:71-86) and reports simple
metrics (GpuMetricNames analogue)."""
from spark_rapids_tpu.execs.base import TpuExec, collect  # noqa: F401
from spark_rapids_tpu.execs.batching import (CoalesceBatchesExec,  # noqa
                                             RequireSingleBatch, TargetSize)
