"""Host <-> device columnar conversions: the transition layer
(GpuRowToColumnarExec.scala / GpuColumnarToRowExec.scala /
HostColumnarToGpu.scala analogues). Host-side data is numpy (+validity);
device side is the bucketed ColumnarBatch."""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.columnar.column import Column, StringColumn


def host_to_batch(data: Dict[str, np.ndarray],
                  validity: Dict[str, Optional[np.ndarray]],
                  schema: Schema, start: int = 0,
                  end: Optional[int] = None,
                  stats: Optional[Dict[str, tuple]] = None
                  ) -> ColumnarBatch:
    """Upload a row range of host columns (the device-upload half of the
    reference's scan path, GpuParquetScan.scala host buffer -> readParquet).
    ``stats``: footer-derived {col: (min, max)} — when provided the
    upload-time host min/max pass is skipped entirely (the footer already
    paid for those numbers during pruning)."""
    import jax

    # build every column's host buffer first, then upload the whole
    # batch in ONE device_put (per-column jnp.asarray each occupies a
    # tunnel round trip; one batched transfer pipelines them)
    host_bufs = []  # flat upload list
    specs = []      # (kind, buf_idx, vmask_idx|None, dtype, dict, stats)
    n = None
    for name, typ in zip(schema.names, schema.types):
        arr = np.asarray(data[name])
        v = validity.get(name)
        sl = slice(start, end)
        arr = arr[sl]
        v = None if v is None else np.asarray(v, dtype=bool)[sl]
        n = len(arr)
        if typ is dt.STRING:
            vals = [None if (v is not None and not v[i]) or arr[i] is None
                    else str(arr[i]) for i in range(n)]
            codes, vmask, dictionary = StringColumn.host_codes(vals)
            bi = len(host_bufs)
            host_bufs.append(codes)
            vi = None
            if vmask is not None:
                vi = len(host_bufs)
                host_bufs.append(vmask)
            specs.append(("str", bi, vi, typ, dictionary, None))
        else:
            if arr.dtype.kind == "M":
                unit = np.datetime_data(arr.dtype)[0]
                arr = (arr.astype("datetime64[D]").astype(np.int32)
                       if typ is dt.DATE else
                       arr.astype("datetime64[us]").astype(np.int64))
            arr = arr.astype(typ.np_dtype)
            col_stats = None
            if typ.is_integral or typ in (dt.DATE, dt.TIMESTAMP):
                s = stats.get(name) if stats is not None else None
                if s is not None:
                    # footer statistics: free bounds, no host pass
                    col_stats = (int(s[0]), int(s[1]))
                else:
                    # upload-time (min, max): one vectorized host pass
                    # that lets the groupby kernel pick its packed-key
                    # sort lane (Column.stats). Also the per-column
                    # fallback when a footer omitted this column's stats
                    sv = arr if v is None else arr[v]
                    if len(sv):
                        col_stats = (int(sv.min()), int(sv.max()))
            buf, vmask, typ = Column.host_buffer(arr, typ, v)
            bi = len(host_bufs)
            host_bufs.append(buf)
            vi = None
            if vmask is not None:
                vi = len(host_bufs)
                host_bufs.append(vmask)
            specs.append(("num", bi, vi, typ, None, col_stats))
    uploaded = jax.device_put(host_bufs)
    cols = []
    for kind, bi, vi, typ, dictionary, col_stats in specs:
        valid = None if vi is None else uploaded[vi]
        if kind == "str":
            cols.append(StringColumn(uploaded[bi], dictionary, valid))
        else:
            cols.append(Column(typ, uploaded[bi], valid,
                               stats=col_stats))
    return ColumnarBatch(cols, n or 0)


def frame_to_batch(frame) -> ColumnarBatch:
    """CpuFrame (cpu/engine.py) -> device batch: the HostColumnarToGpu
    boundary when a CPU-fallback subtree feeds a TPU subtree."""
    cols = []
    for c in frame.cols:
        valid = c.valid_mask()
        if c.dtype is dt.STRING:
            vals = [c.data[i] if valid[i] else None
                    for i in range(frame.num_rows)]
            cols.append(StringColumn.from_strings(vals))
        else:
            v = None if c.validity is None else valid
            cols.append(Column.from_numpy(
                np.asarray(c.data, dtype=c.dtype.np_dtype),
                dtype=c.dtype, validity=v))
    return ColumnarBatch(cols, frame.num_rows)


def batch_to_frame(batch: ColumnarBatch, schema: Schema):
    """Device batch -> CpuFrame: the GpuBringBackToHost boundary when a TPU
    subtree feeds a CPU-fallback operator."""
    from spark_rapids_tpu.cpu.engine import CpuFrame
    from spark_rapids_tpu.cpu.evaluator import CV

    n = batch.realized_num_rows()
    cols = []
    for c, typ in zip(batch.columns, schema.types):
        data, validity = c.to_numpy(n)
        if typ is dt.STRING:
            data = np.asarray(data, dtype=object)
            if validity is None:
                validity = np.array([x is not None for x in data],
                                    dtype=bool)
        cols.append(CV(typ, data, validity))
    return CpuFrame(schema, cols, n)
