"""Host <-> device columnar conversions: the transition layer
(GpuRowToColumnarExec.scala / GpuColumnarToRowExec.scala /
HostColumnarToGpu.scala analogues). Host-side data is numpy (+validity);
device side is the bucketed ColumnarBatch."""
from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.columnar.column import Column, StringColumn


# --------------------------------------------------------------------------
# transfer packing: ship fewer bytes through the host->device pipe
#
# Measured through the axon tunnel the REAL host->device bandwidth is
# ~20-45 MB/s (block_until_ready returns early under the relay; a
# dependent-fetch probe gives the honest number), so a 6M-row TPC-H q1
# scan (~264 MB full-width) costs ~8 s of pure transfer. The reference
# faces the same wall on the PCIe/network edge and ships nvcomp-
# compressed buffers (GpuCompressedColumnVector, shuffle/spill); a TPU
# cannot LZ4-decode on device, but it CAN widen/gather, so the TPU-native
# compression is structural: string dictionary codes at the dictionary's
# width, integers offset-narrowed to their footer-stat span, cents-exact
# doubles as scaled-decimal integers, validity bitmasks bit-packed 8x.
# One jitted program per batch undoes it all on device (a single extra
# dispatch, only paid when something actually packed).
# --------------------------------------------------------------------------

_PACK_MIN_ROWS = 1 << 16      # below this the decode dispatch isn't worth it
_FDICT_MAX_VALUES = 60_000    # value-table ceiling (u16 codes + slack)


def _narrow_uint(span: float):
    if span < 0 or (isinstance(span, float) and not np.isfinite(span)):
        return None
    if span <= 0xFF:
        return np.uint8
    if span <= 0xFFFF:
        return np.uint16
    if span <= 0xFFFFFFFF:
        return np.uint32
    return None


def _pack_fdict(arr: np.ndarray, v) -> Optional[tuple]:
    """f64 -> (narrow code buf, f64 value table) when the column has few
    distinct values (TPC discount/tax/quantity shapes). Decode is ONE
    table gather — pure data movement, the only bit-exact way to
    reproduce arbitrary f64 on this backend: measured, every TPU f64
    ARITHMETIC op (convert, add, mul, div) rounds at float-float
    ~2^-49 precision, and u64 bitcasts are rejected by the x64
    rewriter, so a fraction like 0.07 (full 52-bit mantissa) can never
    be COMPUTED on device — only moved. The round trip is verified
    bit-exactly host-side before the encoding is chosen (this also
    rejects mixed -0.0/0.0 and multi-payload NaN columns, which a
    value table would collapse)."""
    step = max(1, len(arr) // 16384)
    if len(np.unique(arr[::step][:16384])) > 4096:
        return None
    import pandas as pd  # hash-based factorize: no 6M-row sort

    codes, vals = pd.factorize(arr, use_na_sentinel=False)
    vals = np.asarray(vals, dtype=np.float64)
    if len(vals) > _FDICT_MAX_VALUES:
        return None
    width = _narrow_uint(len(vals) - 1)
    if width is None or width().itemsize >= arr.dtype.itemsize:
        return None
    if not (vals[codes].view(np.uint64) == arr.view(np.uint64)).all():
        return None
    enc = codes.astype(width)
    if v is not None:
        enc[~v] = 0
    return enc, vals


def unpack_arrays(bufs, bases, spec, cap):
    """TRACEABLE decode core shared by the standalone unpack program and
    fused chain programs that inline the decode as their first steps
    (the scan->filter->... stage then starts from the packed buffers
    and pays zero decode dispatch)."""
    return _unpack_program(bufs, bases, spec=spec, cap=cap)


def _unpack_program(bufs, bases, *, spec, cap):
    """One jitted device decode for a whole packed batch: widen + offset
    (ints — exact: integer ops are true 32-bit-pair arithmetic), f64
    value-table gather (exact: data movement), narrow string codes to
    i32, validity bit-unpack. bases ride as traced scalar operands so
    one compilation serves every batch at this (spec, shapes)
    signature. Spec entries carry the column's validity-buffer index
    (or -1) so null slots decode to the dtype's sentinel, preserving
    Column.host_buffer's defense-in-depth normalization, plus the
    value-table buffer index for fdict columns."""
    import jax.numpy as jnp

    def unmask(i):
        mbuf, (mkind, _o, _m, _t) = bufs[i], spec[i]
        if mkind != "bits":
            return mbuf
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = (mbuf[:, None] >> shifts[None, :]) & jnp.uint8(1)
        return bits.astype(jnp.bool_).reshape(-1)[:cap]

    outs = []
    for buf, base, (kind, out_name, mi, ti) in zip(bufs, bases, spec):
        if kind == "raw":
            outs.append(buf)
        elif kind == "widen":
            out_dt = np.dtype(out_name)
            out = buf.astype(out_dt) + jnp.asarray(base).astype(out_dt)
            if mi >= 0:
                # integral sentinel is 0 (dtypes.null_sentinel)
                out = jnp.where(unmask(mi), out, jnp.asarray(0, out_dt))
            outs.append(out)
        elif kind == "fdict":
            out = jnp.take(bufs[ti], buf.astype(jnp.int32))
            if mi >= 0:
                out = jnp.where(unmask(mi), out, jnp.float64(jnp.nan))
            outs.append(out)
        elif kind == "codes":
            outs.append(buf.astype(jnp.int32))
        elif kind == "bits":
            outs.append(unmask(len(outs)))
        else:  # pragma: no cover - spec is engine-built
            raise AssertionError(kind)
    return tuple(outs)


_UNPACK_JIT = None


def _get_unpack_jit():
    """The jitted decode, created once (a fresh jax.jit wrapper per call
    would key a fresh trace cache and recompile every batch)."""
    global _UNPACK_JIT
    if _UNPACK_JIT is None:
        import jax

        _UNPACK_JIT = partial(jax.jit,
                              static_argnames=("spec", "cap"))(
            _unpack_program)
    return _UNPACK_JIT


class PackedHost:
    """Host-side result of ``pack_host``: everything needed to upload
    and decode one batch, with NO device interaction yet. Produced on
    scan worker threads so the (pure-CPU) encode overlaps the previous
    batch's tunnel transfer and device compute."""

    __slots__ = ("host_bufs", "dec_specs", "dec_bases", "col_specs",
                 "cap", "n")

    def __init__(self, host_bufs, dec_specs, dec_bases, col_specs,
                 cap, n):
        self.host_bufs = host_bufs
        self.dec_specs = dec_specs
        self.dec_bases = dec_bases
        self.col_specs = col_specs
        self.cap = cap
        self.n = n

    def nbytes(self) -> int:
        """Host bytes staged for upload — what a queued slice charges
        the admission budget while it waits in the scan pipeline."""
        return int(sum(b.nbytes for b in self.host_bufs))


class PackedBatch:
    """Device-resident but still PACKED scan batch: the upload happened
    (one device_put) and the decode is deferred into the consumer's own
    compiled program — a fused chain inlines ``unpack_arrays`` as its
    first traced steps, so scan-decode + filter + join + project run as
    ONE dispatch. Only fusion-aware consumers understand this type;
    everything else must call ``decode()`` (one unpack dispatch, the
    exact program the eager path would have run)."""

    __slots__ = ("bufs", "dec_specs", "dec_bases", "col_specs",
                 "capacity", "num_rows", "origin")

    def __init__(self, bufs, dec_specs, dec_bases, col_specs, cap, n):
        self.bufs = list(bufs)
        self.dec_specs = tuple(dec_specs)
        self.dec_bases = tuple(dec_bases)
        self.col_specs = list(col_specs)
        self.capacity = cap
        self.num_rows = n
        self.origin = None

    @property
    def num_columns(self) -> int:
        return len(self.col_specs)

    def realized_num_rows(self) -> int:
        return self.num_rows

    def num_rows_device(self):
        import jax.numpy as jnp

        return jnp.asarray(self.num_rows, dtype=jnp.int32)

    def decode_key(self):
        """Static program-cache key component: which buffer decodes how
        and which decoded slots form each output column."""
        return (self.dec_specs,
                tuple((kind, bi, -1 if vi is None else vi)
                      for kind, bi, vi, _t, _d, _s in self.col_specs),
                self.capacity)

    def ghost_info(self):
        """Per output column (dtype, dictionary, stats) — the host
        mirror a fused chain's ghost walk starts from."""
        return [(typ, dictionary, col_stats)
                for _k, _bi, _vi, typ, dictionary, col_stats
                in self.col_specs]

    def column_arrays(self, decoded):
        """Map decoded flat buffers to per-column (data, validity)
        pairs, in col_specs order."""
        out = []
        for _kind, bi, vi, _typ, _d, _s in self.col_specs:
            out.append((decoded[bi],
                        None if vi is None else decoded[vi]))
        return out

    def decode(self) -> ColumnarBatch:
        """Standalone decode (one dispatch) — the safety valve for any
        consumer that is not fusion-aware."""
        decoded = list(_get_unpack_jit()(
            tuple(self.bufs), tuple(self.dec_bases),
            spec=self.dec_specs, cap=self.capacity))
        b = _wrap_uploaded(decoded, self.col_specs, self.num_rows)
        b.origin = self.origin
        return b


def _wrap_uploaded(uploaded, col_specs, n) -> ColumnarBatch:
    cols = []
    for kind, bi, vi, typ, dictionary, col_stats in col_specs:
        valid = None if vi is None else uploaded[vi]
        if kind == "str":
            cols.append(StringColumn(uploaded[bi], dictionary, valid))
        else:
            cols.append(Column(typ, uploaded[bi], valid,
                               stats=col_stats))
    return ColumnarBatch(cols, n)


def pack_host(data: Dict[str, np.ndarray],
              validity: Dict[str, Optional[np.ndarray]],
              schema: Schema, start: int = 0,
              end: Optional[int] = None,
              stats: Optional[Dict[str, tuple]] = None,
              pack: bool = True) -> PackedHost:
    """Host half of the upload: slice, encode and (when it pays) pack
    every column into flat transfer buffers. Pure CPU work — safe on a
    worker thread, touches no device state."""
    from spark_rapids_tpu.io.hoststrings import HostStrings
    from spark_rapids_tpu.ops.buckets import bucket_capacity

    host_bufs = []   # flat upload list (possibly packed)
    dec_specs = []   # per buf: (kind, out_dtype_name, mask_idx, tbl_idx)
    dec_bases = []   # per buf: traced scalar operand
    specs = []       # (kind, buf_idx, vmask_idx|None, dtype, dict, stats)
    n = None
    cap = None

    def push(buf, kind, out_name, base=0, mi=-1, ti=-1):
        host_bufs.append(buf)
        dec_specs.append((kind, out_name, mi, ti))
        dec_bases.append(base)
        return len(host_bufs) - 1

    def push_vmask(v):
        """Pad + (when packing pays) bit-pack a validity mask."""
        vm = np.zeros(cap, dtype=bool)
        vm[:n] = v
        if do_pack:
            return push(np.packbits(vm, bitorder="little"), "bits", "")
        return push(vm, "raw", "")

    for name, typ in zip(schema.names, schema.types):
        raw = data[name]
        arr = raw if isinstance(raw, HostStrings) else np.asarray(raw)
        v = validity.get(name)
        sl = slice(start, end)
        arr = arr[sl]
        v = None if v is None else np.asarray(v, dtype=bool)[sl]
        if n is None:
            n = len(arr)
            cap = bucket_capacity(n)
            do_pack = pack and n >= _PACK_MIN_ROWS
        if typ is dt.STRING:
            if isinstance(arr, HostStrings):
                # already dictionary-encoded by the scan: pad + upload,
                # zero host passes over row-wise Python strings
                codes_n = np.where(v, arr.codes, 0) \
                    if v is not None else arr.codes
                dictionary = arr.dictionary
                v_eff = v if (v is not None and not v.all()) else None
            else:
                vals = [None
                        if (v is not None and not v[i]) or arr[i] is None
                        else str(arr[i]) for i in range(n)]
                c32, vm32, dictionary = StringColumn.host_codes(vals)
                codes_n = c32[:n]
                # host_codes derives nulls from the None values too —
                # its mask, not the caller's, is authoritative here
                v_eff = vm32[:n] if vm32 is not None else None
            # max code is len(dictionary)-1 (same convention as
            # _pack_fdict), so exactly-256/65536-entry dictionaries
            # still pack as u8/u16
            width = _narrow_uint(max(len(dictionary) - 1, 0)) \
                if do_pack else None
            if width is not None and width().itemsize < 4:
                codes = np.zeros(cap, dtype=width)
                codes[:n] = codes_n.astype(width)
                bi = push(codes, "codes", "")
            else:
                codes = np.zeros(cap, dtype=np.int32)
                codes[:n] = codes_n
                bi = push(codes, "raw", "")
            vi = None
            if v_eff is not None:
                vi = push_vmask(v_eff)
            specs.append(("str", bi, vi, typ, dictionary, None))
        else:
            if arr.dtype.kind == "M":
                unit = np.datetime_data(arr.dtype)[0]
                arr = (arr.astype("datetime64[D]").astype(np.int32)
                       if typ is dt.DATE else
                       arr.astype("datetime64[us]").astype(np.int64))
            arr = arr.astype(typ.np_dtype, copy=False)
            col_stats = None
            if typ.is_integral or typ in (dt.DATE, dt.TIMESTAMP):
                s = stats.get(name) if stats is not None else None
                if s is not None:
                    # footer statistics: free bounds, no host pass
                    col_stats = (int(s[0]), int(s[1]))
                else:
                    # upload-time (min, max): one vectorized host pass
                    # that lets the groupby kernel pick its packed-key
                    # sort lane (Column.stats). Also the per-column
                    # fallback when a footer omitted this column's stats
                    sv = arr if v is None else arr[v]
                    if len(sv):
                        col_stats = (int(sv.min()), int(sv.max()))
            kname = np.dtype(typ.kernel_dtype).name
            # mask first: packed data columns reference it to decode
            # null slots to the dtype sentinel
            vi = push_vmask(v) if v is not None else None
            mi = -1 if vi is None else vi
            bi = None
            if do_pack and col_stats is not None and \
                    typ is not dt.BOOLEAN:
                lo, hi = col_stats
                width = _narrow_uint(hi - lo)
                if width is not None and \
                        width().itemsize < arr.dtype.itemsize:
                    t = arr.astype(np.int64, copy=False) - lo
                    if v is not None:
                        t[~v] = 0  # t is fresh (the subtract allocates)
                    enc = np.zeros(cap, dtype=width)
                    enc[:n] = t.astype(width)
                    bi = push(enc, "widen", kname, base=int(lo), mi=mi)
            if bi is None and do_pack and typ is dt.FLOAT64:
                packed = _pack_fdict(arr, v)
                if packed is not None:
                    encv, table = packed
                    enc = np.zeros(cap, dtype=encv.dtype)
                    enc[:n] = encv
                    # pad the value table to a power-of-two length so
                    # table-size wobble between batches doesn't key a
                    # fresh decode compilation
                    tlen = max(1, len(table))
                    tcap = 1 << (tlen - 1).bit_length()
                    tbuf = np.zeros(tcap, dtype=np.float64)
                    tbuf[:tlen] = table
                    ti = push(tbuf, "raw", kname)
                    bi = push(enc, "fdict", kname, mi=mi, ti=ti)
            if bi is None:
                buf, _vm, typ = Column.host_buffer(arr, typ, v,
                                                   capacity=cap)
                bi = push(buf, "raw", kname)
            specs.append(("num", bi, vi, typ, None, col_stats))

    return PackedHost(host_bufs, tuple(dec_specs), tuple(dec_bases),
                      specs, cap or 0, n or 0)


def upload_packed(packed: PackedHost, defer_decode: bool = False):
    """Device half of the upload: ONE device_put for the whole batch's
    buffers (per-column jnp.asarray would each occupy a tunnel round
    trip; one batched transfer pipelines them), then the jitted decode
    — or, with ``defer_decode``, a PackedBatch that hands the decode to
    a fusion-aware consumer's own program (zero decode dispatch)."""
    import jax

    uploaded = jax.device_put(packed.host_bufs)
    if any(s[0] != "raw" for s in packed.dec_specs):
        if defer_decode:
            return PackedBatch(uploaded, packed.dec_specs,
                               packed.dec_bases, packed.col_specs,
                               packed.cap, packed.n)
        uploaded = list(_get_unpack_jit()(
            tuple(uploaded), tuple(packed.dec_bases),
            spec=packed.dec_specs, cap=packed.cap))
    return _wrap_uploaded(uploaded, packed.col_specs, packed.n)


def host_to_batch(data: Dict[str, np.ndarray],
                  validity: Dict[str, Optional[np.ndarray]],
                  schema: Schema, start: int = 0,
                  end: Optional[int] = None,
                  stats: Optional[Dict[str, tuple]] = None,
                  pack: bool = True, defer_decode: bool = False):
    """Upload a row range of host columns (the device-upload half of the
    reference's scan path, GpuParquetScan.scala host buffer ->
    readParquet). ``stats``: footer-derived {col: (min, max)} — when
    provided the upload-time host min/max pass is skipped entirely (the
    footer already paid for those numbers during pruning). ``pack``:
    transfer packing (see module comment above); packed buffers decode
    on device in one jitted program per batch — or inside the consuming
    fused chain's program when ``defer_decode``."""
    return upload_packed(
        pack_host(data, validity, schema, start, end, stats, pack),
        defer_decode=defer_decode)


def frame_to_batch(frame) -> ColumnarBatch:
    """CpuFrame (cpu/engine.py) -> device batch: the HostColumnarToGpu
    boundary when a CPU-fallback subtree feeds a TPU subtree."""
    cols = []
    for c in frame.cols:
        valid = c.valid_mask()
        if c.dtype is dt.STRING:
            vals = [c.data[i] if valid[i] else None
                    for i in range(frame.num_rows)]
            cols.append(StringColumn.from_strings(vals))
        else:
            v = None if c.validity is None else valid
            cols.append(Column.from_numpy(
                np.asarray(c.data, dtype=c.dtype.np_dtype),
                dtype=c.dtype, validity=v))
    return ColumnarBatch(cols, frame.num_rows)


def batch_to_frame(batch: ColumnarBatch, schema: Schema):
    """Device batch -> CpuFrame: the GpuBringBackToHost boundary when a TPU
    subtree feeds a CPU-fallback operator."""
    from spark_rapids_tpu.cpu.engine import CpuFrame
    from spark_rapids_tpu.cpu.evaluator import CV

    n = batch.realized_num_rows()
    cols = []
    for c, typ in zip(batch.columns, schema.types):
        data, validity = c.to_numpy(n)
        if typ is dt.STRING:
            data = np.asarray(data, dtype=object)
            if validity is None:
                validity = np.array([x is not None for x in data],
                                    dtype=bool)
        cols.append(CV(typ, data, validity))
    return CpuFrame(schema, cols, n)
