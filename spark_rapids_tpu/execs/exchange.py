"""Exchange execs: shuffle repartitioning and broadcast.

Reference: GpuShuffleExchangeExecBase partitions batches on device then
registers (partId, subBatch) pairs with the caching shuffle writer
(GpuShuffleExchangeExec.scala:146-248, RapidsShuffleInternalManager.scala:
90-155) — sub-batches are catalog-registered and spillable at priority 0;
readers take local device hits zero-copy (RapidsCachingReader.scala:59-145).

Single-process version: the shuffle "transport" is a per-exec block store of
SpillableBatch handles (the local-catalog-hit path). The multi-host bulk
path rides the mesh all_to_all in parallel/shuffle.py.
"""
from __future__ import annotations

import threading
from spark_rapids_tpu.utils import lockorder
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.memory import priorities
from spark_rapids_tpu.memory.spillable import SpillableBatch
from spark_rapids_tpu.ops import partition as part_ops
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.utils.tracing import TraceRange


def partition_batch(b: ColumnarBatch, partitioning: Tuple, types,
                    num_out: int) -> Tuple[ColumnarBatch, np.ndarray]:
    """Partition one batch: returns (destination-sorted batch, per-
    partition counts). Shared by the in-process exchange and the cluster
    runtime's map tasks (local and remote-worker alike)."""
    kind = partitioning[0]
    if kind == "hash":
        return part_ops.hash_partition(b, list(partitioning[1]), types,
                                       num_out)
    if kind == "round_robin":
        return part_ops.round_robin_partition(b, num_out)
    if kind == "range":
        specs: List[SortKeySpec] = list(partitioning[1])
        bounds = partitioning[2]
        if len(specs) > 1:
            return part_ops.range_partition_multi(b, specs, types,
                                                  bounds, num_out)
        return part_ops.range_partition(b, specs, types, bounds, num_out)
    if kind == "single":
        return part_ops.single_partition(b)
    raise ValueError(kind)


class ShuffleExchangeExec(TpuExec):
    """partitioning: ('hash', key_ordinals) | ('range', specs) |
    ('round_robin',) | ('single',)."""

    def __init__(self, partitioning: Tuple, num_out_partitions: int,
                 child: TpuExec, task_threads: int = 1,
                 batch_bytes: Optional[int] = None):
        super().__init__([child], child.schema)
        self.partitioning = partitioning
        self.num_out_partitions = num_out_partitions
        # bound for the range-exchange tiny-input collapse: the staged
        # input must fit ONE configured batch for a single sort task to
        # be the right plan (conf batchSizeBytes when the planner wires
        # it; capped by the spill chunk budget either way)
        self.collapse_bytes = min(
            self.CHUNK_BYTE_BUDGET,
            batch_bytes if batch_bytes is not None
            else self.CHUNK_BYTE_BUDGET)
        # default 1 (serial): concurrency is an OPT-IN the planner wires
        # from rapids.tpu.sql.taskThreads — unplumbed construction sites
        # (python-UDF exchanges running arbitrary user code, tests) must
        # not silently multithread
        self.task_threads = task_threads
        # block store: output partition -> spillable sub-batches
        self._blocks: Optional[Dict[int, List[SpillableBatch]]] = None
        # in-program mode (SPMD whole-stage exchange): the map side runs
        # as ONE compiled hash-route + all_to_all program over the mesh
        # instead of per-batch partition kernels + per-partition slices.
        # apply_overrides flips this on for eligible hash exchanges via
        # enable_in_program(); parallel/spmd.py owns the eligibility
        # decision and records every "no" with a reason.
        self.in_program = False
        self._in_program_mesh = None
        # AQE skew spec (parallel.spmd.SkewSpec) — when set, the
        # in-program map side detects hot reduce partitions host-side
        # (the input is already gathered for the collective) and salts
        # them across the device axis before the all_to_all
        self._skew_spec = None
        # reduce tasks run on concurrent threads; the map side must
        # materialize exactly once (Spark serializes this via stage
        # boundaries — here a lock is the stage barrier). A condition
        # rather than a bare lock: the in-program path runs its device
        # program OUTSIDE the lock (no device transfer while a
        # framework lock is held) and late arrivals wait on it.
        self._mat_lock = lockorder.make_condition(
            "exchange.shuffle.materialize")
        self._mat_running = False

    # an exchange shipping inside a remote task closure restarts clean:
    # blocks are per-process state (the receiving executor re-runs or
    # cluster-reads; cluster exchanges are stubbed out before pickling)
    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_mat_lock", None)
        state["_mat_running"] = False
        state["_blocks"] = None
        # meshes are process-local device handles; a shipped exchange
        # re-decides on the receiving side (cluster mode shuffles over
        # TCP anyway — the spmd gate never enables both)
        state["in_program"] = False
        state["_in_program_mesh"] = None
        state["_skew_spec"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._mat_lock = lockorder.make_condition(
            "exchange.shuffle.materialize")

    def enable_in_program(self, mesh, skew=None) -> None:
        """Switch the map side to the compiled all_to_all program over
        ``mesh``. Partition count and per-row partition assignment are
        unchanged (the step reproduces the host partition kernel's pid
        exactly), so consumers — including a co-partitioned sibling
        exchange that stays on the host path — see identical blocks.

        ``skew`` (a parallel.spmd.SkewSpec) arms AQE salting: reduce
        partitions whose measured map-output bytes exceed the skew cut
        are spread across ALL devices by the collective instead of
        landing on ``pid % n_dev`` — the pid column is untouched, only
        the routing changes, so the per-partition blocks sliced after
        the collective are still exact."""
        assert self.partitioning[0] == "hash", self.partitioning
        assert self._blocks is None, "already materialized"
        from spark_rapids_tpu.parallel import spmd

        # per-exchange seam record (the plan-time gate records the
        # decision; this records an exchange actually ARMED onto it)
        spmd.record_seam("exchange", spmd.SEAM_ICI,
                         "in-program all_to_all armed over the "
                         "session mesh slice")
        self.in_program = True
        self._in_program_mesh = mesh
        self._skew_spec = skew

    @property
    def num_partitions(self) -> int:
        # range exchanges replan adaptively: the first partition-count
        # query OUTSIDE planning (collect's pre-execution walk)
        # materializes the map side, and _materialize collapses to ONE
        # partition when the staged input fits a single batch budget —
        # a global sort over a final aggregate's handful of rows must
        # not pay bounds sampling + range partitioning + N sort tasks
        # (AQE's materialize-then-replan, applied to the sort stage).
        if self.partitioning[0] == "range" and self._blocks is None:
            from spark_rapids_tpu.execs import adaptive as adaptive_exec

            if not adaptive_exec.planning_active():
                self._materialize()
        return self.num_out_partitions

    def _partition_batch(self, b: ColumnarBatch
                         ) -> Tuple[ColumnarBatch, np.ndarray]:
        return partition_batch(b, self.partitioning,
                               list(self.schema.types),
                               self.num_out_partitions)

    def _materialize(self) -> None:
        """Map-side write: run the child once, cache partitioned blocks
        (RapidsCachingWriter.write). Child partitions run as concurrent
        map tasks on the task pool (device entry gated by the shared
        TpuSemaphore inside the execs). Range partitioning with
        unresolved bounds stages the input (spillable) and samples bounds
        host-side first — the reference runs a separate sampling pass the
        same way (GpuRangePartitioner.scala:42-95)."""
        if self.in_program and self._in_program_mesh is not None:
            self._materialize_in_program_once()
            if self._blocks is not None:
                return
            # a device error degraded this exchange (in_program is now
            # False): fall through to the host/TCP path, once per query
        with self._mat_lock:
            if self._blocks is not None:
                return
            if self.partitioning[0] == "range" and \
                    (len(self.partitioning) < 3 or
                     self.partitioning[2] is None):
                from spark_rapids_tpu.execs.base import run_partitions

                def stage_task(in_p: int):
                    return [SpillableBatch(
                        b, priorities.INPUT_FROM_SHUFFLE_PRIORITY)
                        for b in self.children[0].execute(in_p)
                        if b.realized_num_rows() > 0]

                staged = [sb for part in run_partitions(
                    self.children[0].num_partitions, stage_task,
                    self.task_threads) for sb in part]
                total_rows = sum(sb.num_rows for sb in staged)
                row_bytes = max(sum(t.byte_width
                                    for t in self.schema.types), 1)
                if self.num_out_partitions > 1 and \
                        total_rows * row_bytes <= self.collapse_bytes:
                    # adaptive collapse: tiny staged input -> single
                    # partition, no bounds sampling, no partition kernel
                    self.num_out_partitions = 1
                    self._blocks = {0: staged}
                    return
                specs = list(self.partitioning[1])
                if len(specs) > 1:
                    bounds = part_ops.sample_range_bounds_rows(
                        staged, specs, list(self.schema.types),
                        self.num_out_partitions)
                else:
                    bounds = part_ops.sample_range_bounds_multi(
                        staged, specs, list(self.schema.types),
                        self.num_out_partitions)
                self.partitioning = ("range", self.partitioning[1],
                                     bounds)
                source = self._drain_staged(staged)
                blocks = self._write_blocks(source)
            else:
                from spark_rapids_tpu.execs.base import run_partitions

                def map_task(in_p: int):
                    # realize lazy counts in bounded chunks so an
                    # out-of-core child never has its whole partition
                    # resident at once — each chunk's batches move into
                    # spillable blocks before the next is read. The
                    # chunk boundary is a BYTE budget estimated from
                    # host-known capacities (no sync to compute), so an
                    # in-core partition of many small batches still pays
                    # its single realize_counts round trip
                    out: Dict[int, List[SpillableBatch]] = {
                        p: [] for p in range(self.num_out_partitions)}
                    chunk: List[ColumnarBatch] = []
                    chunk_bytes = 0

                    def flush():
                        nonlocal chunk_bytes
                        ColumnarBatch.realize_counts(chunk)
                        self._write_blocks(
                            (b for b in chunk
                             if b.realized_num_rows() > 0), into=out)
                        chunk.clear()
                        chunk_bytes = 0

                    for b in self.children[0].execute(in_p):
                        chunk.append(b)
                        chunk_bytes += \
                            b.capacity * max(b.num_columns, 1) * 8
                        if chunk_bytes >= self.CHUNK_BYTE_BUDGET:
                            flush()
                    if chunk:
                        flush()
                    return out

                # merge per-map outputs in PARTITION order, not thread
                # completion order: float aggregates downstream must see
                # a deterministic batch order or a recomputed shared
                # subtree (tpch q15's revenue view) sums to a different
                # last-ulp value than its sibling
                outs = run_partitions(self.children[0].num_partitions,
                                      map_task, self.task_threads)
                blocks = {p: [] for p in range(self.num_out_partitions)}
                for out in outs:
                    for p, subs in out.items():
                        blocks[p].extend(subs)
            self._blocks = blocks

    # estimated resident bytes a map task may stage before realizing
    # counts and moving the chunk into spillable blocks
    CHUNK_BYTE_BUDGET = 256 << 20

    def _materialize_in_program_once(self) -> None:
        """Single-flight wrapper for the in-program map side: the
        compiled program and its host<->device transfers run OUTSIDE
        the materialize lock (holding a framework lock across a device
        transfer stalls every sibling reduce task for the transfer's
        full RTT); late arrivals wait on the condition instead of
        re-running the program."""
        with self._mat_lock:
            while self._mat_running:
                self._mat_lock.wait()
            # a waiter wakes to either a materialized exchange or one
            # the leader DEGRADED (in_program cleared) — both mean the
            # in-program attempt is over for this query
            if self._blocks is not None or not self.in_program:
                return
            self._mat_running = True
        blocks = None
        try:
            blocks = self._materialize_in_program()
        except Exception as e:
            from spark_rapids_tpu.parallel import spmd

            if not spmd.is_degradable_device_error(e):
                raise
            # SPMD degrade: a device error inside the compiled exchange
            # program falls back to the host/TCP path for this stage —
            # once per query (in_program stays off) — instead of
            # failing the query on a path that has a lossless fallback
            from spark_rapids_tpu.runtime import recovery

            spmd.record_degrade("exchange")
            recovery.bump("spmd_degrades")
            self.in_program = False
            self._in_program_mesh = None
        finally:
            with self._mat_lock:
                self._mat_running = False
                if blocks is not None and self._blocks is None:
                    self._blocks = blocks
                self._mat_lock.notify_all()

    def _materialize_in_program(self) -> Dict[int, List[SpillableBatch]]:
        """Map-side write over the mesh: stage child rows once, run ONE
        compiled hash-route + ``all_to_all`` program, slice each
        device's received block into that partition's store. Three
        dispatches total (staging gather, the program, result gather)
        regardless of batch or partition count — the host path pays a
        partition kernel per batch plus a slice per partition."""
        import jax
        from spark_rapids_tpu.memory.fault_injection import get_injector
        from spark_rapids_tpu.parallel import shuffle as pshuffle
        from spark_rapids_tpu.parallel.mesh import DATA_AXIS

        # deterministic degrade fence: the OOM injector can fail this
        # site (InjectedOOM classifies as a device error) so the
        # SPMD-degrade path runs on CPU CI without a real XLA fault
        get_injector().maybe_inject("exchange.inProgram")
        mesh = self._in_program_mesh
        n_dev = mesh.shape[DATA_AXIS]
        num_out = self.num_out_partitions
        types = list(self.schema.types)
        blocks: Dict[int, List[SpillableBatch]] = {
            p: [] for p in range(num_out)}
        batches = list(self._input_batches())
        ColumnarBatch.realize_counts(batches)
        batches = [b for b in batches if b.realized_num_rows() > 0]
        if not batches:
            return blocks
        # ONE host gather for every staged batch's columns (pytree get);
        # device_get returns host ndarrays, so everything below is pure
        # numpy with no further transfers
        host = jax.device_get(
            [[(c.data, c.validity) for c in b.columns] for b in batches])
        ns = [b.realized_num_rows() for b in batches]
        arrays, valids = [], []
        for ci in range(len(types)):
            arrays.append(np.concatenate(
                [host[bi][ci][0][:n] for bi, n in enumerate(ns)]))
            valids.append(np.concatenate(
                [np.ones(n, dtype=bool) if host[bi][ci][1] is None
                 else host[bi][ci][1][:n]
                 for bi, n in enumerate(ns)]))
        salt = self._salt_pids(arrays, valids, types)
        datas, vs, counts = pshuffle.distributed_batch_from_host(
            mesh, arrays, types, validities=valids)[:3]
        step = pshuffle.shuffle_step(mesh, types,
                                     list(self.partitioning[1]), num_out,
                                     salt_pids=salt)
        with TraceRange("ShuffleExchangeExec.all_to_all"):
            out_d, out_v, pids, recv = step(datas, vs, counts)
        hd, hv, hp, hn = jax.device_get(
            (list(out_d), list(out_v), pids, recv))
        rcap = len(hd[0]) // n_dev
        from spark_rapids_tpu.ops.buckets import bucket_capacity
        from spark_rapids_tpu.columnar.column import Column

        for d in range(n_dev):
            k = int(hn[d])
            if k == 0:
                continue
            seg = slice(d * rcap, d * rcap + k)
            seg_pids = hp[seg]
            # split the device's compacted block into per-partition
            # sub-blocks (pure numpy — no extra dispatch). Unsalted,
            # device d holds exactly the pids with pid % n_dev == d;
            # a SALTED pid arrives on every device, so enumerate the
            # pids actually present instead of the modular ladder
            for p in np.unique(seg_pids):
                p = int(p)
                idx = np.nonzero(seg_pids == p)[0]
                cap = bucket_capacity(len(idx))
                cols = [Column.from_numpy(
                    hd[ci][seg][idx], t,
                    validity=hv[ci][seg][idx],
                    capacity=cap) for ci, t in enumerate(types)]
                blocks[p].append(SpillableBatch(
                    ColumnarBatch(cols, len(idx)),
                    priorities.OUTPUT_FOR_SHUFFLE_PRIORITY))
        return blocks

    def _salt_pids(self, arrays, valids, types) -> Tuple[int, ...]:
        """Hot reduce-partition ids for the in-program map side, from a
        host mirror of the device partition hash over the already-
        gathered input. Empty when skew handling is off or nothing
        crosses the cut. Capped at 16 pids (largest first) — the salt
        set is a compile-time constant of the shuffle program and an
        unbounded set would fragment the program cache."""
        spec = self._skew_spec
        if spec is None or not arrays or not len(arrays[0]):
            return ()
        from spark_rapids_tpu.execs import adaptive as adaptive_exec
        from spark_rapids_tpu.ops import hashing

        pids = hashing.host_partition_ids(
            arrays, valids, types, list(self.partitioning[1]),
            self.num_out_partitions)
        row_bytes = max(sum(t.byte_width + 1 for t in types), 1)
        sizes = np.bincount(
            pids, minlength=self.num_out_partitions) * row_bytes
        stats = adaptive_exec.MapOutputStatistics(
            [int(s) for s in sizes])
        hot = stats.skewed_partitions(spec.factor, spec.threshold)
        if not hot:
            return ()
        hot = sorted(hot, key=lambda p: -sizes[p])[:16]
        for p in sorted(hot):
            adaptive_exec.record_replan(
                "skew_salt", f"partition {p} salted across mesh")
        return tuple(sorted(hot))

    def _write_blocks(self, source, into=None
                      ) -> Dict[int, List[SpillableBatch]]:
        blocks: Dict[int, List[SpillableBatch]] = into if into is not None \
            else {p: [] for p in range(self.num_out_partitions)}
        for b in source:
            with TraceRange("ShuffleExchangeExec.partition"):
                sorted_b, counts = self._partition_batch(b)
                subs = part_ops.slice_partitions(sorted_b, counts)
            for p, sub in enumerate(subs):
                if sub is None:
                    continue
                blocks[p].append(SpillableBatch(
                    sub, priorities.OUTPUT_FOR_SHUFFLE_PRIORITY))
        return blocks

    def map_output_sizes(self) -> List[int]:
        """Per-reduce-partition byte sizes of the materialized map output
        (MapStatus sizes; cluster exchanges answer from the tracker)."""
        assert self._blocks is not None, "materialize first"
        return [sum(h.device_memory_size() for h in self._blocks[p])
                for p in range(self.num_out_partitions)]

    def _input_batches(self):
        for in_p in range(self.children[0].num_partitions):
            for b in self.children[0].execute(in_p):
                if b.realized_num_rows() == 0:
                    continue
                yield b

    @staticmethod
    def _drain_staged(staged: List[SpillableBatch]):
        for sb in staged:
            with sb.acquired() as b:
                yield b
            sb.close()

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            self._materialize()
            handles = self._blocks[partition]
            if not handles:
                yield ColumnarBatch.empty(self.schema)
                return
            for h in handles:
                with h.acquired() as batch:
                    yield batch
        return timed(self, it())


class BroadcastExchangeExec(TpuExec):
    """Materializes the whole child once as a single batch, replayed to
    every consumer partition (GpuBroadcastExchangeExec.scala:237-380; the
    cached batch is spillable like the reference's host-serialized form)."""

    def __init__(self, child: TpuExec):
        super().__init__([child], child.schema)
        self._cached: Optional[SpillableBatch] = None
        self._mat_lock = lockorder.make_lock("exchange.broadcast.materialize")

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_mat_lock", None)
        state["_cached"] = None  # re-materializes on the receiving side
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._mat_lock = lockorder.make_lock("exchange.broadcast.materialize")

    @property
    def num_partitions(self) -> int:
        return 1

    @property
    def coalesce_after(self):
        from spark_rapids_tpu.execs.batching import RequireSingleBatch

        return RequireSingleBatch

    def _materialize(self) -> SpillableBatch:
        with self._mat_lock:
            return self._materialize_locked()

    def _materialize_locked(self) -> SpillableBatch:
        if self._cached is None:
            batches = []
            for p in range(self.children[0].num_partitions):
                batches.extend(self.children[0].execute(p))
            if len(batches) > 1:
                # one batched realize for ALL counts (was one host sync
                # per child batch), then drop empties before the concat
                ColumnarBatch.realize_counts(batches)
                batches = [b for b in batches
                           if b.realized_num_rows() > 0]
            if len(batches) == 1:
                # single batch: no concat, and the count can stay a
                # lazy device scalar — build prep consumes it as a
                # traced operand, so the whole broadcast+prep path
                # runs without a host sync of its own
                merged = batches[0]
            elif batches:
                merged = concat_batches(batches)
            else:
                merged = ColumnarBatch.empty(self.schema)
            self._cached = SpillableBatch(
                merged, priorities.INPUT_FROM_SHUFFLE_PRIORITY,
                defer_count=True)
        return self._cached

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            with self._materialize().acquired() as batch:
                yield batch
        return timed(self, it())
