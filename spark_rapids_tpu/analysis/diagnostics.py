"""Diagnostic codes and the Finding record every pass emits.

Codes are STABLE: allowlist entries and baselines reference them, so a
code is never renumbered or reused. New checks take the next free
number in their family.
"""
from __future__ import annotations

import dataclasses

#: code -> one-line meaning. The authoritative list; docs/static-analysis.md
#: renders this table and tests assert the two never drift.
CODES = {
    # -- TPU1xx: host-sync discipline -----------------------------------
    "TPU101": "np.asarray/np.array on device data without an explicit "
              "jax.device_get (hidden device->host sync)",
    "TPU102": ".item() scalar pull (one full dispatch RTT per call)",
    "TPU103": "block_until_ready outside benchmark/measurement code",
    "TPU104": "implicit __bool__ on a jnp array value (truth test "
              "forces a sync)",
    # -- TPU2xx: recompile hazards --------------------------------------
    "TPU201": "jax.jit created inside a function body (fresh trace "
              "cache per call: recompiles every invocation)",
    "TPU202": "data-dependent shape fed to an array constructor in a "
              "function that never quantizes through ops/buckets",
    "TPU203": "jnp scalar/array literal without an explicit dtype "
              "(weak-type promotion drifts program signatures)",
    "TPU204": "pallas_call outside the native/kernels registry wrapper "
              "(bypasses the interpret-mode gate: dead code on CPU CI "
              "or a crash off-TPU)",
    # -- TPU3xx: concurrency --------------------------------------------
    "TPU301": "lock acquisition order inverts the declared hierarchy "
              "(utils/lockorder.py)",
    "TPU302": "blocking call (device transfer, socket I/O, sleep, "
              "foreign Condition.wait) while holding a framework lock",
    "TPU303": "lock created outside utils/lockorder factories, or with "
              "an undeclared hierarchy name",
    # -- TPU4xx: robustness / config ------------------------------------
    "TPU401": "except handler can swallow RESOURCE_EXHAUSTED without "
              "re-raising into the retry ladder (memory/retry.py)",
    "TPU402": "rapids.tpu.* knob string not registered in config.py",
    "TPU403": "registered knob missing from docs/configs.md (run "
              "scripts/gen_config_docs.py)",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic at one site. ``scope`` is the allowlist key for
    the site (``<relpath>::<qualname>`` or just ``<relpath>`` for
    module-level findings)."""

    code: str
    path: str        # path relative to the repo root
    line: int
    qualname: str    # enclosing function/class qualname, "" at module level
    message: str

    @property
    def scope(self) -> str:
        return f"{self.path}::{self.qualname}" if self.qualname else self.path

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        if self.qualname:
            where += f" ({self.qualname})"
        return f"{self.code} {where}: {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)
