"""Shared AST plumbing for the tpulint passes.

Each pass walks every module under the package root once; this module
owns source discovery, parsing, qualname attribution, and the small
call-graph used by the lock pass. Everything is stdlib ``ast`` — the
linter must run in a bare CPU CI container with no extra deps.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Tuple

#: Modules the code passes never scan: the analysis package itself
#: (its fixtures and docstrings mention every anti-pattern by name).
SKIP_PREFIXES = ("spark_rapids_tpu/analysis/",)


def package_root() -> str:
    """Repo-root directory containing ``spark_rapids_tpu/``."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def iter_modules(root: str) -> Iterator[Tuple[str, ast.Module, str]]:
    """Yield (relpath, parsed AST, source) for every package module
    under ``root``. ``root`` is a directory that contains a
    ``spark_rapids_tpu`` tree OR any directory of .py files (the
    seeded-violation fences point this at a temp tree)."""
    pkg = os.path.join(root, "spark_rapids_tpu")
    scan = pkg if os.path.isdir(pkg) else root
    for dirpath, dirnames, filenames in os.walk(scan):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__", ".git"))
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, root)
            if any(rel.startswith(p) for p in SKIP_PREFIXES):
                continue
            with open(full, encoding="utf-8") as f:
                src = f.read()
            try:
                tree = ast.parse(src, filename=rel)
            except SyntaxError:
                continue  # not our job; CI's compile step reports it
            yield rel, tree, src


class QualnameVisitor(ast.NodeVisitor):
    """Base visitor that tracks the enclosing def/class qualname, so
    findings attribute to ``Class.method`` allowlist scopes."""

    def __init__(self):
        self._stack: List[str] = []

    @property
    def qualname(self) -> str:
        return ".".join(self._stack)

    def _push(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def visit_FunctionDef(self, node):
        self._push(node)

    def visit_AsyncFunctionDef(self, node):
        self._push(node)

    def visit_ClassDef(self, node):
        self._push(node)


def dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` attribute chains as a string, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted(node.func)


def collect_functions(tree: ast.Module) -> Dict[str, ast.AST]:
    """qualname -> def node for every function/method in a module."""
    out: Dict[str, ast.AST] = {}

    class V(QualnameVisitor):
        def _push(self, node):
            super()._push(node)

        def visit_FunctionDef(self, node):
            self._stack.append(node.name)
            out[".".join(self._stack)] = node
            self.generic_visit(node)
            self._stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

    V().visit(tree)
    return out


def local_calls(fn_node: ast.AST) -> List[str]:
    """Names this function calls, as dotted strings (``self.foo`` and
    bare ``foo`` both reported) — the intraprocedural call-graph edge
    list used by the lock pass."""
    out = []
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name:
                out.append(name)
    return out
