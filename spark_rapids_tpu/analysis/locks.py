"""TPU3xx: lock discipline, statically.

The declared hierarchy lives in ``utils/lockorder.py`` (single source
of truth — the runtime assertion proxy reads the same tables). This
pass extracts every ``with <lock>:`` nesting across a per-module call
graph and checks:

- TPU301 the inner lock's rank must exceed the outer's (same-group
  plan barriers and same-name nestable locks exempt, mirroring the
  runtime rules);
- TPU302 no blocking call — device transfer, socket I/O, sleep, a
  ``wait`` on anything that isn't the held lock's own condition —
  while a framework lock is held;
- TPU303 every lock is created through the ``lockorder`` factories
  with a name the hierarchy declares (a raw ``threading.Lock()`` is
  invisible to both enforcement layers).

The call graph is per-module and name-resolved (``self.meth`` within
the same class, bare names at module level): deep enough to catch the
real pattern (a ``with`` body calling a helper that transfers), cheap
enough to run on every CI push.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from spark_rapids_tpu.analysis import astutil
from spark_rapids_tpu.analysis.diagnostics import Finding
from spark_rapids_tpu.utils.lockorder import (
    GROUPS, LOCK_HIERARCHY, NESTABLE)

_FACTORIES = {"lockorder.make_lock": "lock",
              "lockorder.make_rlock": "rlock",
              "lockorder.make_condition": "condition"}
_RAW = {"threading.Lock", "threading.RLock", "threading.Condition"}

#: modules allowed to create raw threading primitives: the factory
#: module itself, and the telemetry installed before everything else.
_RAW_OK = ("spark_rapids_tpu/utils/lockorder.py",)

_BLOCKING_ATTRS = {"recv", "recv_into", "sendall", "accept", "connect",
                   "device_get", "device_put", "block_until_ready"}
_BLOCKING_DOTTED = {"time.sleep", "jax.device_get", "jax.device_put",
                    "jax.block_until_ready"}


def _order_ok(outer: str, inner: str) -> bool:
    """Mirror of _TrackedLock._check: may ``inner`` be acquired while
    ``outer`` is held?"""
    g_out, g_in = GROUPS.get(outer), GROUPS.get(inner)
    if g_in is not None and g_in == g_out:
        return True
    ro, ri = LOCK_HIERARCHY[outer], LOCK_HIERARCHY[inner]
    if ri > ro:
        return True
    return ri == ro and inner == outer and inner in NESTABLE


class _ModuleLocks:
    """Lock-name resolution tables for one module."""

    def __init__(self, tree: ast.Module, rel: str,
                 findings: List[Finding]):
        self.globals: Dict[str, str] = {}
        self.attrs: Dict[Tuple[str, str], str] = {}
        self.functions = astutil.collect_functions(tree)

        class V(astutil.QualnameVisitor):
            def visit_Assign(v, node):
                if isinstance(node.value, ast.Call):
                    fname = astutil.call_name(node.value)
                    if fname in _FACTORIES:
                        self._record(node, v.qualname, rel, findings)
                    elif fname in _RAW and rel not in _RAW_OK:
                        findings.append(Finding(
                            code="TPU303", path=rel, line=node.lineno,
                            qualname=v.qualname,
                            message=f"{fname}() bypasses the lockorder "
                                    f"factories — invisible to both the "
                                    f"static and runtime hierarchy "
                                    f"checks"))
                v.generic_visit(node)

            def visit_Call(v, node):
                # raw creations not in assignments (e.g. default args)
                fname = astutil.call_name(node)
                if fname in _RAW and rel not in _RAW_OK and \
                        not isinstance(getattr(node, "_parent", None),
                                       ast.Assign):
                    pass  # assignments handled above; flag the rest
                v.generic_visit(node)

        # mark assignment value nodes so visit_Call skips them
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for sub in ast.walk(node.value):
                    sub._parent = node
        V().visit(tree)
        # raw creations OUTSIDE assignments (inline `with
        # threading.Lock():`, getattr fallbacks)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    astutil.call_name(node) in _RAW and \
                    rel not in _RAW_OK and \
                    not isinstance(getattr(node, "_parent", None),
                                   ast.Assign):
                findings.append(Finding(
                    code="TPU303", path=rel, line=node.lineno,
                    qualname="",
                    message=f"{astutil.call_name(node)}() bypasses the "
                            f"lockorder factories"))

    def _record(self, assign: ast.Assign, qualname: str, rel: str,
                findings: List[Finding]):
        call = assign.value
        name_arg = call.args[0] if call.args else None
        if not (isinstance(name_arg, ast.Constant) and
                isinstance(name_arg.value, str)):
            findings.append(Finding(
                code="TPU303", path=rel, line=assign.lineno,
                qualname=qualname,
                message="lockorder factory called with a non-literal "
                        "name — the hierarchy cannot be checked"))
            return
        lock_name = name_arg.value
        if lock_name not in LOCK_HIERARCHY:
            findings.append(Finding(
                code="TPU303", path=rel, line=assign.lineno,
                qualname=qualname,
                message=f"lock name {lock_name!r} is not declared in "
                        f"utils/lockorder.py LOCK_HIERARCHY"))
            return
        for t in assign.targets:
            if isinstance(t, ast.Name):
                self.globals[t.id] = lock_name
            elif isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id == "self" and qualname:
                cls = qualname.split(".")[0]
                self.attrs[(cls, t.attr)] = lock_name

    def resolve(self, expr: ast.AST, qualname: str) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return self.globals.get(expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and qualname:
            return self.attrs.get((qualname.split(".")[0], expr.attr))
        return None

    def resolve_callee(self, call_name: str,
                       qualname: str) -> Optional[str]:
        if call_name.startswith("self."):
            cand = qualname.split(".")[0] + "." + call_name[5:]
            if cand in self.functions:
                return cand
        if call_name in self.functions:
            return call_name
        return None


def _walk_with_bodies(mod: _ModuleLocks, qualname: str, body,
                      emit, held: List[str],
                      visited_fns: Set[str]) -> None:
    """Walk statements with ``held`` (outermost-first lock names) in
    effect; recurse into nested withs and same-module callees."""
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.With):
                inner_names = []
                for item in node.items:
                    nm = mod.resolve(item.context_expr, qualname)
                    if nm:
                        inner_names.append((nm, node))
                for nm, wnode in inner_names:
                    for h in held:
                        if not _order_ok(h, nm):
                            emit("TPU301", wnode, qualname,
                                 f"acquires {nm!r} (rank "
                                 f"{LOCK_HIERARCHY[nm]}) while "
                                 f"{h!r} (rank {LOCK_HIERARCHY[h]}) "
                                 f"is held — inverts the declared "
                                 f"hierarchy")
                # note: ast.walk re-visits nested bodies; the recursion
                # below carries the extended held-set, and the dedup in
                # the gate collapses the duplicate shallow visit
            elif isinstance(node, ast.Call) and held:
                cn = astutil.call_name(node) or ""
                blocking = (cn in _BLOCKING_DOTTED or
                            cn.split(".")[-1] in _BLOCKING_ATTRS or
                            cn.endswith(".wait"))
                if cn.endswith(".wait"):
                    # waiting on the held lock's OWN condition releases
                    # it — that's what conditions are for
                    target = mod.resolve(node.func.value, qualname) \
                        if isinstance(node.func, ast.Attribute) else None
                    if target is not None and target == held[-1]:
                        blocking = False
                if blocking:
                    emit("TPU302", node, qualname,
                         f"blocking call {cn}(...) while "
                         f"{held[-1]!r} is held")
                callee = mod.resolve_callee(cn, qualname)
                if callee and callee not in visited_fns:
                    visited_fns.add(callee)
                    fn = mod.functions[callee]
                    _walk_with_bodies(mod, callee, fn.body, emit,
                                      held, visited_fns)

    # second pass: recurse into each with body with the lock pushed
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.With):
                names = [mod.resolve(i.context_expr, qualname)
                         for i in node.items]
                names = [n for n in names if n]
                if names:
                    _walk_with_bodies(mod, qualname, node.body, emit,
                                      held + names, set(visited_fns))


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple] = set()

    for rel, tree, _src in astutil.iter_modules(root):
        if rel.endswith("utils/lockorder.py"):
            continue
        mod = _ModuleLocks(tree, rel, findings)
        if not (mod.globals or mod.attrs):
            continue

        def emit(code, node, qualname, msg, rel=rel):
            key = (code, rel, qualname, msg)
            if key in seen:
                return
            seen.add(key)
            findings.append(Finding(
                code=code, path=rel, line=node.lineno,
                qualname=qualname, message=msg))

        for qn, fn in mod.functions.items():
            _walk_with_bodies(mod, qn, fn.body, emit, [], {qn})
    return findings
