"""TPU1xx: host-sync discipline.

Behind the axon tunnel every device->host sync costs a full ~105 ms
dispatch RTT (BASELINE.md), so implicit syncs are the single largest
class of invisible regression: they cost nothing on a local CPU run
and >10% of a query on the real hardware. The contract this pass
enforces: device data crosses to the host ONLY through an explicit
``jax.device_get`` at an allowlisted staging/collect site.

- TPU101 ``np.asarray``/``np.array`` on anything that could be a device
  array (the numpy coercion of a jax array is a silent blocking
  transfer). Literal/host-constructor arguments are exempt; a direct
  ``np.asarray(jax.device_get(x))`` is exempt (the sync is explicit).
- TPU102 ``.item()`` — one scalar, one full RTT.
- TPU103 ``block_until_ready`` — a barrier; legitimate only in
  benchmark/measurement code.
- TPU104 implicit ``__bool__`` on a value assigned from a ``jnp.*``
  call (``if jnp.any(...)``, ``while not done`` over a device flag):
  the truth test syncs without any visible transfer call.
"""
from __future__ import annotations

import ast
from typing import List

from spark_rapids_tpu.analysis import astutil
from spark_rapids_tpu.analysis.diagnostics import Finding

_NP_COERCE = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}

#: jnp functions that return host metadata (python bools), not device
#: arrays — their truth test is free
_JNP_METADATA = {"jnp.issubdtype", "jnp.isdtype",
                 "jax.numpy.issubdtype", "jax.numpy.isdtype"}

#: argument node types that are host data by construction
_HOST_LITERALS = (ast.List, ast.Tuple, ast.Constant, ast.ListComp,
                  ast.GeneratorExp, ast.Dict, ast.Set)


def _arg_is_explicit_host(arg: ast.AST) -> bool:
    if isinstance(arg, _HOST_LITERALS):
        return True
    if isinstance(arg, ast.Call):
        name = astutil.call_name(arg) or ""
        if name.endswith("device_get"):
            return True  # explicit sync: the point of the rule
        # any other call: numpy/host helpers dominate; a jnp.* result
        # fed straight to np.asarray is still flagged
        return not (name.startswith("jnp.") or
                    name.startswith("jax.numpy"))
    return False


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []

    for rel, tree, _src in astutil.iter_modules(root):

        class V(astutil.QualnameVisitor):
            def __init__(self):
                super().__init__()
                # names assigned from jnp.* calls in the current scope
                self._device_names: List[set] = [set()]

            def _push(self, node):
                self._device_names.append(set())
                super()._push(node)
                self._device_names.pop()

            def _emit(self, code, node, msg):
                findings.append(Finding(
                    code=code, path=rel, line=node.lineno,
                    qualname=self.qualname, message=msg))

            def visit_Assign(self, node):
                if isinstance(node.value, ast.Call):
                    name = astutil.call_name(node.value) or ""
                    if name.startswith("jnp.") or \
                            name.startswith("jax.numpy"):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                self._device_names[-1].add(t.id)
                self.generic_visit(node)

            def visit_Call(self, node):
                name = astutil.call_name(node)
                if name in _NP_COERCE and node.args and \
                        not _arg_is_explicit_host(node.args[0]):
                    self._emit(
                        "TPU101", node,
                        f"{name}(...) may coerce a device array to "
                        f"host without an explicit jax.device_get")
                elif name and name.endswith(".item") and not node.args:
                    self._emit(
                        "TPU102", node,
                        ".item() pulls one scalar at a full dispatch "
                        "RTT; batch into one device_get")
                elif name and name.endswith("block_until_ready"):
                    self._emit(
                        "TPU103", node,
                        "block_until_ready barrier outside "
                        "benchmark/measurement code")
                self.generic_visit(node)

            def _check_truth(self, test):
                node = test
                if isinstance(node, ast.UnaryOp) and \
                        isinstance(node.op, ast.Not):
                    node = node.operand
                if isinstance(node, ast.Name) and any(
                        node.id in s for s in self._device_names):
                    self._emit(
                        "TPU104", test,
                        f"truth test on {node.id!r} (assigned from a "
                        f"jnp.* call) forces an implicit sync")
                elif isinstance(node, ast.Call):
                    name = astutil.call_name(node) or ""
                    if (name.startswith("jnp.") or
                            name.startswith("jax.numpy")) and \
                            name not in _JNP_METADATA:
                        self._emit(
                            "TPU104", test,
                            f"truth test on {name}(...) result forces "
                            f"an implicit sync")

            def visit_If(self, node):
                self._check_truth(node.test)
                self.generic_visit(node)

            def visit_While(self, node):
                self._check_truth(node.test)
                self.generic_visit(node)

            def visit_Assert(self, node):
                self._check_truth(node.test)
                self.generic_visit(node)

        V().visit(tree)
    return findings
