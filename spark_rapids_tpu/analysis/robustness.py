"""TPU4xx: robustness and config-knob consistency.

- TPU401 an ``except Exception`` (or bare ``except``) on the device
  path that neither re-raises nor consults
  ``memory.retry.is_oom_error`` can swallow RESOURCE_EXHAUSTED — the
  retry ladder (PR 6) then never sees the OOM and the query dies (or
  silently degrades) instead of splitting. Import guards (try bodies
  that only import) are exempt: no device call can raise there.
- TPU402 every ``rapids.tpu.*`` string literal must resolve against
  the live config registry (``plan/overrides`` imported first so the
  per-op flag families are registered): a typo'd knob silently no-ops.
- TPU403 every registered knob must appear in ``docs/configs.md``
  (regenerate with ``scripts/gen_config_docs.py``).
"""
from __future__ import annotations

import ast
import os
import re
from typing import List

from spark_rapids_tpu.analysis import astutil
from spark_rapids_tpu.analysis.diagnostics import Finding

#: the device path: an OOM can only surface under these trees
_DEVICE_PATH = ("spark_rapids_tpu/execs/", "spark_rapids_tpu/service/",
                "spark_rapids_tpu/memory/", "spark_rapids_tpu/runtime/",
                "spark_rapids_tpu/shuffle/", "spark_rapids_tpu/parallel/",
                "spark_rapids_tpu/ops/")

#: a full knob key: no trailing dot, so key-family PREFIX strings
#: ("rapids.tpu.sql.") used to build dynamic names don't match
_KNOB_RE = re.compile(
    r"^rapids\.tpu\.[A-Za-z0-9_]+(\.[A-Za-z0-9_]+)+$")


def _registered_keys():
    """(all registered keys, keys requiring documentation) with the
    import-time per-op flag families registered first. Docs-required =
    the import-time snapshot minus ``internal()`` entries — exactly
    what gen_config_docs.py emits: it skips internals, and apply-time
    per-node flags (an open set) never exist in its fresh process."""
    import spark_rapids_tpu.plan.overrides  # noqa: F401  registers op flags
    from spark_rapids_tpu import config

    snapshot = config.snapshot_docs_registry()
    documented = {e.key for e in config.registered_entries()
                  if not e.internal and e.key in snapshot}
    return set(config._REGISTRY), documented


def _is_import_guard(try_node: ast.Try) -> bool:
    return all(isinstance(s, (ast.Import, ast.ImportFrom))
               for s in try_node.body)


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in ("Exception", "BaseException")
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and
                   e.id in ("Exception", "BaseException")
                   for e in t.elts)
    return False


def _handler_reraises_or_gates(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = astutil.call_name(node) or ""
            if name.split(".")[-1] == "is_oom_error":
                return True
    return False


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []
    registry, documented = _registered_keys()

    for rel, tree, _src in astutil.iter_modules(root):
        on_device_path = any(rel.startswith(p) for p in _DEVICE_PATH)

        class V(astutil.QualnameVisitor):
            def _emit(self, code, node, msg):
                findings.append(Finding(
                    code=code, path=rel, line=node.lineno,
                    qualname=self.qualname, message=msg))

            def visit_Try(self, node):
                if on_device_path and not _is_import_guard(node):
                    for h in node.handlers:
                        if _handler_is_broad(h) and \
                                not _handler_reraises_or_gates(h):
                            self._emit(
                                "TPU401", h,
                                "broad except without re-raise or "
                                "is_oom_error gate can swallow "
                                "RESOURCE_EXHAUSTED before the retry "
                                "ladder sees it")
                self.generic_visit(node)

            def visit_Constant(self, node):
                if isinstance(node.value, str) and \
                        _KNOB_RE.match(node.value) and \
                        node.value not in registry:
                    self._emit(
                        "TPU402", node,
                        f"knob string {node.value!r} is not registered "
                        f"in config.py — a typo here silently no-ops")

        V().visit(tree)

    # TPU403: registry vs docs/configs.md (only when scanning the real
    # repo — a seeded fixture tree has no docs to cross-check)
    docs_path = os.path.join(root, "docs", "configs.md")
    if os.path.exists(docs_path):
        with open(docs_path, encoding="utf-8") as f:
            doc_text = f.read()
        for key in sorted(documented):
            if key not in doc_text:
                findings.append(Finding(
                    code="TPU403", path="docs/configs.md", line=1,
                    qualname="",
                    message=f"registered knob {key!r} is undocumented "
                            f"— run scripts/gen_config_docs.py"))
    return findings
