"""The per-site allowlist: every accepted finding carries a written
justification, reviewed like code.

Format (``allowlist.txt``, one entry per line)::

    CODE <scope> -- <justification>

where ``<scope>`` is one of

- ``path/to/file.py::Qual.Name`` — one function/method (preferred),
- ``path/to/file.py``            — a whole module,
- ``path/prefix/*``              — every module under a directory
  (reserved for tooling that exists to perform the flagged operation,
  e.g. the benchmark harness syncing on purpose).

The ``--`` justification is MANDATORY: a bare scope is a parse error,
so "allowlist it" is never cheaper than writing down why it's safe.
Blank lines and ``#`` comments are ignored.
"""
from __future__ import annotations

import os
from typing import Dict, List, Tuple

from spark_rapids_tpu.analysis.diagnostics import CODES, Finding

DEFAULT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "allowlist.txt")


class AllowlistError(ValueError):
    pass


class Allowlist:
    def __init__(self, entries: List[Tuple[str, str, str]]):
        #: (code, scope, justification)
        self.entries = entries
        self._exact: Dict[Tuple[str, str], str] = {}
        self._globs: List[Tuple[str, str, str]] = []
        for code, scope, just in entries:
            if scope.endswith("/*"):
                self._globs.append((code, scope[:-1], just))
            else:
                self._exact[(code, scope)] = just

    @classmethod
    def parse(cls, text: str, origin: str = "<allowlist>") -> "Allowlist":
        entries = []
        for i, raw in enumerate(text.splitlines(), 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if "--" not in line:
                raise AllowlistError(
                    f"{origin}:{i}: missing '-- justification' "
                    f"(justifications are mandatory): {line!r}")
            head, just = line.split("--", 1)
            just = just.strip()
            if not just:
                raise AllowlistError(
                    f"{origin}:{i}: empty justification: {line!r}")
            parts = head.split()
            if len(parts) != 2:
                raise AllowlistError(
                    f"{origin}:{i}: expected 'CODE scope -- why': {line!r}")
            code, scope = parts
            if code not in CODES:
                raise AllowlistError(
                    f"{origin}:{i}: unknown diagnostic code {code!r}")
            entries.append((code, scope, just))
        return cls(entries)

    @classmethod
    def load(cls, path: str = DEFAULT_PATH) -> "Allowlist":
        if not os.path.exists(path):
            return cls([])
        with open(path) as f:
            return cls.parse(f.read(), origin=path)

    def allows(self, finding: Finding) -> bool:
        if (finding.code, finding.scope) in self._exact:
            return True
        if (finding.code, finding.path) in self._exact:
            return True
        return any(code == finding.code and finding.path.startswith(prefix)
                   for code, prefix, _ in self._globs)

    def filter(self, findings: List[Finding]) -> List[Finding]:
        """The findings NOT covered by this allowlist."""
        return [f for f in findings if not self.allows(f)]

    def unused_entries(self, findings: List[Finding]) -> List[Tuple[str, str, str]]:
        """Entries matching no finding — stale justifications that
        should be deleted when the underlying site is fixed."""
        out = []
        for code, scope, just in self.entries:
            if scope.endswith("/*"):
                prefix = scope[:-1]
                hit = any(f.code == code and f.path.startswith(prefix)
                          for f in findings)
            else:
                hit = any(f.code == code and scope in (f.scope, f.path)
                          for f in findings)
            if not hit:
                out.append((code, scope, just))
        return out
