"""TPU2xx: recompile hazards.

One compile per (program, bucket shape) is the serving layer's core
contract (PR 7): a jit entry point whose operand shapes bypass the
``ops/buckets`` capacity ladder compiles per DISTINCT RAW SIZE — the
exact bug class that made a lazily-compiled 2-way coalesced program a
0.4 s p99 outlier. Recompiles behind the tunnel cost seconds to
minutes, so the hazards are flagged statically:

- TPU201 ``jax.jit`` called inside a function body: the returned
  callable's trace cache dies with it, so every invocation re-traces
  (and usually re-compiles). Module-level jits — including the
  memoized-global idiom ``execs/interop.py`` uses — are the fix.
- TPU202 array constructor (``jnp.zeros``/``ones``/``full``/``empty``)
  whose shape derives from ``len(...)`` or ``.shape`` in a function
  that never quantizes through ``bucket_capacity``: raw data-dependent
  shapes mint unbounded signatures.
- TPU203 ``jnp.asarray``/``jnp.array`` of a bare numeric literal with
  no ``dtype``: weak-type promotion makes the operand's signature
  depend on surrounding arithmetic, so structurally identical programs
  stop sharing executables (x64 drift doubles the damage).
- TPU204 ``pallas_call`` not routed through the
  ``native/kernels`` registry wrapper: the registry pins
  ``interpret=True`` off-TPU so CPU CI executes the same kernel bodies
  that compile for TPU. A direct ``pl.pallas_call`` site either
  dead-codes its CPU leg or crashes on a non-TPU backend — and its
  interpret decision can drift from the process-wide policy.
"""
from __future__ import annotations

import ast
import os
from typing import List, Set, Tuple

from spark_rapids_tpu.analysis import astutil
from spark_rapids_tpu.analysis.diagnostics import Finding

_CONSTRUCTORS = {"jnp.zeros", "jnp.ones", "jnp.full", "jnp.empty",
                 "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.full",
                 "jax.numpy.empty"}
_LITERAL_WRAP = {"jnp.asarray", "jnp.array",
                 "jax.numpy.asarray", "jax.numpy.array"}

#: the one module allowed to touch pl.pallas_call directly (it IS the
#: interpret-mode gate); everything else must call its wrapper
_KERNEL_REGISTRY_MOD = "spark_rapids_tpu.native.kernels"
_KERNEL_REGISTRY_FILE = os.path.join(
    "spark_rapids_tpu", "native", "kernels", "__init__.py")


def _registry_aliases(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
    """(module aliases, function aliases) this module binds to the
    native-kernel registry / its ``pallas_call`` wrapper — receivers a
    ``pallas_call`` site may legitimately go through."""
    mods: Set[str] = set()
    fns: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == _KERNEL_REGISTRY_MOD and a.asname:
                    mods.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "spark_rapids_tpu.native":
                for a in node.names:
                    if a.name == "kernels":
                        mods.add(a.asname or "kernels")
            elif node.module == _KERNEL_REGISTRY_MOD:
                for a in node.names:
                    if a.name == "pallas_call":
                        fns.add(a.asname or "pallas_call")
    return mods, fns


def _decorator_nodes(tree: ast.Module) -> Set[int]:
    """ids of every node inside a decorator list: ``@partial(jax.jit,
    ...)`` is the SANCTIONED module-level idiom, not a TPU201."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        for dec in getattr(node, "decorator_list", ()) or ():
            for sub in ast.walk(dec):
                out.add(id(sub))
    return out


def _shape_is_data_dependent(call: ast.Call) -> bool:
    """Does the constructor's shape argument derive from len()/.shape?"""
    if not call.args:
        return False
    for node in ast.walk(call.args[0]):
        if isinstance(node, ast.Call) and \
                astutil.call_name(node) == "len":
            return True
        if isinstance(node, ast.Attribute) and node.attr == "shape":
            return True
    return False


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []

    for rel, tree, _src in astutil.iter_modules(root):
        in_decorator = _decorator_nodes(tree)
        is_registry = rel.replace(os.sep, "/").endswith(
            "spark_rapids_tpu/native/kernels/__init__.py")
        registry_mods, registry_fns = _registry_aliases(tree)
        functions = astutil.collect_functions(tree)
        # functions that (transitively locally) reach bucket_capacity
        quantizers = {
            qn for qn, fn in functions.items()
            if any(c.split(".")[-1] == "bucket_capacity"
                   for c in astutil.local_calls(fn))}

        class V(astutil.QualnameVisitor):
            def _emit(self, code, node, msg):
                findings.append(Finding(
                    code=code, path=rel, line=node.lineno,
                    qualname=self.qualname, message=msg))

            def visit_Call(self, node):
                name = astutil.call_name(node)
                if name in ("jax.jit", "jit") and self.qualname and \
                        id(node) not in in_decorator:
                    self._emit(
                        "TPU201", node,
                        "jax.jit inside a function body re-traces per "
                        "call; hoist to module level (see "
                        "execs/interop.py's memoized-global idiom)")
                elif name in _CONSTRUCTORS and \
                        _shape_is_data_dependent(node) and \
                        self.qualname not in quantizers:
                    self._emit(
                        "TPU202", node,
                        f"{name} shape derives from len()/.shape in a "
                        f"function that never calls bucket_capacity — "
                        f"raw sizes mint one executable per distinct "
                        f"length")
                elif name in _LITERAL_WRAP and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, (int, float)) and \
                        len(node.args) < 2 and \
                        not any(kw.arg == "dtype"
                                for kw in node.keywords):
                    self._emit(
                        "TPU203", node,
                        f"{name}({node.args[0].value!r}) without dtype "
                        f"is weakly typed; the promoted signature "
                        f"drifts with surrounding arithmetic")
                elif name and not is_registry and \
                        (name == "pallas_call" or
                         name.endswith(".pallas_call")):
                    receiver = name.rsplit(".", 1)[0] if "." in name \
                        else None
                    sanctioned = (
                        receiver in registry_mods or
                        receiver == _KERNEL_REGISTRY_MOD or
                        (receiver is None and name in registry_fns))
                    if not sanctioned:
                        self._emit(
                            "TPU204", node,
                            f"{name} bypasses the native/kernels "
                            f"registry wrapper — its interpret-mode "
                            f"gate is what keeps the kernel body live "
                            f"(and correct) on non-TPU backends")
                self.generic_visit(node)

        V().visit(tree)
    return findings
