"""tpulint: static analysis passes for the invariants runtime fences
can't reliably reach.

The framework's performance and safety contracts — no hidden host
round-trips inside a fused stage (ROADMAP item 2), one compile per
(program, bucket) (PR 7), device OOM always reaches the retry ladder
(PR 6), no lock-order inversions across the ~40 framework locks — are
structural properties of the source. This package checks them at
analysis time, on CPU, with stable diagnostic codes:

- ``TPU1xx`` host-sync discipline (:mod:`.host_sync`)
- ``TPU2xx`` recompile hazards (:mod:`.recompile`)
- ``TPU3xx`` lock-order / blocking-under-lock (:mod:`.locks`)
- ``TPU4xx`` robustness + config-knob consistency (:mod:`.robustness`)

plus a plan-level sync map (:mod:`.plan_sync`) that walks
``plan/optimizer.cut_stages`` output and names, per pipeline stage,
every operator that forces a device->host round trip.

Findings outside ``allowlist.txt`` (per-site entries, justification
mandatory) fail the CI gate ``scripts/lint_check.py``. Workflow and
code reference: docs/static-analysis.md.
"""
from spark_rapids_tpu.analysis.diagnostics import (  # noqa: F401
    CODES, Finding)
from spark_rapids_tpu.analysis.allowlist import Allowlist  # noqa: F401


def run_all(pkg_root=None):
    """Run every pass over the package tree rooted at ``pkg_root``
    (default: the installed spark_rapids_tpu sources); returns the raw
    (un-allowlisted) findings sorted by location."""
    from spark_rapids_tpu.analysis import (
        host_sync, locks, recompile, robustness)
    from spark_rapids_tpu.analysis.astutil import package_root

    root = pkg_root or package_root()
    findings = []
    findings += host_sync.run(root)
    findings += recompile.run(root)
    findings += locks.run(root)
    findings += robustness.run(root)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings
