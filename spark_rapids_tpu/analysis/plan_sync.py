"""Plan-level sync map: which operators in a COMPILED plan force a
device->host round trip, per pipeline stage.

The AST passes see source; this walk sees the actual exec tree a query
will run, labeled by ``plan/optimizer.cut_stages``. Output is the
static round-trip map ROADMAP item 2's ``vs_cpu_oracle`` work needs:
every sync a stage will pay, named, BEFORE the query runs. For tpcxbb
q26 at sf 0.1 the map is exactly two entries — the fused join chain's
batched duplicate-flag fetch and the root result fetch — and
``tests/test_analysis.py`` fences that it stays exactly those two.

Classification (kind -> why it syncs):

- ``duplicate-flag fetch`` — an exec with broadcast ``builds``:
  ``execs/fused.prepare_builds`` must host-check the build-side
  duplicate-key flag once per query (batched over all builds).
- ``result fetch`` — the root exec: ``collect`` materializes the
  result to host by definition.
- ``UDF host round-trip`` — python/pandas execs ship batches to a
  worker process and back.
- ``CPU fallback transition`` — device->host->device around the
  pandas engine.
- ``mesh shard/gather staging`` — multi-device mesh execs stage
  shards through the host.
"""
from __future__ import annotations

from typing import List


def _classify(exec_node, is_root: bool) -> List[str]:
    kinds = []
    cls = type(exec_node).__name__
    if getattr(exec_node, "builds", None):
        kinds.append("duplicate-flag fetch")
    if is_root:
        kinds.append("result fetch")
    if "InPandas" in cls or "EvalPython" in cls:
        kinds.append("UDF host round-trip")
    if cls == "CpuFallbackExec":
        kinds.append("CPU fallback transition")
    if cls.startswith("Mesh"):
        kinds.append("mesh shard/gather staging")
    return kinds


def sync_map(root) -> List[dict]:
    """[{stage, op, kind}] for every sync-forcing operator reachable
    from ``root`` (children and broadcast builds), in stage order.
    Labels every exec via cut_stages as a side effect."""
    from spark_rapids_tpu.plan.optimizer import cut_stages

    cut_stages(root)  # assigns _stage_label to every exec
    out: List[dict] = []
    seen = set()

    def walk(node, is_root):
        if id(node) in seen:
            return
        seen.add(id(node))
        for kind in _classify(node, is_root):
            out.append({
                "stage": getattr(node, "_stage_label", "<unlabeled>"),
                "op": type(node).__name__,
                "kind": kind,
            })
        for c in node.children:
            walk(c, False)
        for bx in getattr(node, "builds", ()) or ():
            walk(bx, False)

    walk(root, True)
    return out


def render(entries: List[dict]) -> str:
    lines = [f"{e['stage']:>8}  {e['kind']:<28} {e['op']}"
             for e in entries]
    return "\n".join(lines) if lines else "(no sync-forcing operators)"
