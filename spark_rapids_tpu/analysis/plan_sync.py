"""Plan-level sync map: which operators in a COMPILED plan force a
device->host round trip, per pipeline stage.

The AST passes see source; this walk sees the actual exec tree a query
will run, labeled by ``plan/optimizer.cut_stages``. Output is the
static round-trip map ROADMAP item 2's ``vs_cpu_oracle`` work needs:
every sync a stage will pay, named, BEFORE the query runs. For tpcxbb
q26 at sf 0.1 the map is exactly two entries — the fused join chain's
batched duplicate-flag fetch and the root result fetch — and
``tests/test_analysis.py`` fences that it stays exactly those two.

Classification (kind -> why it syncs):

- ``duplicate-flag fetch`` — an exec with broadcast ``builds``:
  ``execs/fused.prepare_builds`` must host-check the build-side
  duplicate-key flag once per query (batched over all builds).
- ``result fetch`` — the root exec: ``collect`` materializes the
  result to host by definition.
- ``UDF host round-trip`` — python/pandas execs ship batches to a
  worker process and back.
- ``CPU fallback transition`` — device->host->device around the
  pandas engine.
- ``mesh shard staging (leaf input)`` — a mesh exec with a non-mesh
  child stages that child's host batches into device shards.
- ``mesh result gather`` — the topmost mesh exec of a chain gathers
  shards back to host for its non-mesh consumer.
- ``mesh exchange map-side staging`` — an in-program
  ``ShuffleExchangeExec`` stages its child's batches through the host
  around ONE compiled all_to_all program (three batched dispatches).

A mesh exec BETWEEN two mesh execs contributes nothing: the sharded
hand-off stays on device and the exchange between them is the
in-program ``all_to_all`` — the SPMD whole-stage path's zero-hidden-
sync guarantee, and ``tests/test_spmd_shuffle.py`` fences it.
"""
from __future__ import annotations

from typing import List


def _is_mesh(node) -> bool:
    return type(node).__name__.startswith("Mesh")


def _classify(exec_node, is_root: bool,
              mesh_parent: bool = False) -> List[str]:
    kinds = []
    cls = type(exec_node).__name__
    if getattr(exec_node, "builds", None):
        kinds.append("duplicate-flag fetch")
    if is_root:
        kinds.append("result fetch")
    if "InPandas" in cls or "EvalPython" in cls:
        kinds.append("UDF host round-trip")
    if cls == "CpuFallbackExec":
        kinds.append("CPU fallback transition")
    if cls.startswith("Mesh"):
        # only the mesh<->host BOUNDARIES sync; mesh-internal execs
        # hand DistributedBatch shards device-to-device (execute_any)
        # and their exchanges run as in-program all_to_all collectives
        if any(not _is_mesh(c) for c in exec_node.children):
            kinds.append("mesh shard staging (leaf input)")
        if not mesh_parent:
            kinds.append("mesh result gather")
    if getattr(exec_node, "in_program", False):
        kinds.append("mesh exchange map-side staging")
    return kinds


def sync_map(root) -> List[dict]:
    """[{stage, op, kind}] for every sync-forcing operator reachable
    from ``root`` (children and broadcast builds), in stage order.
    Labels every exec via cut_stages as a side effect."""
    from spark_rapids_tpu.plan.optimizer import cut_stages

    cut_stages(root)  # assigns _stage_label to every exec
    out: List[dict] = []
    seen = set()

    def walk(node, is_root, mesh_parent):
        if id(node) in seen:
            return
        seen.add(id(node))
        for kind in _classify(node, is_root, mesh_parent):
            out.append({
                "stage": getattr(node, "_stage_label", "<unlabeled>"),
                "op": type(node).__name__,
                "kind": kind,
            })
        for c in node.children:
            walk(c, False, _is_mesh(node))
        for bx in getattr(node, "builds", ()) or ():
            walk(bx, False, _is_mesh(node))

    walk(root, True, False)
    return out


def render(entries: List[dict]) -> str:
    lines = [f"{e['stage']:>8}  {e['kind']:<28} {e['op']}"
             for e in entries]
    return "\n".join(lines) if lines else "(no sync-forcing operators)"
