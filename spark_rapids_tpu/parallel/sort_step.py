"""Distributed global sort over the device mesh.

The reference's distributed ORDER BY: sample range bounds on the driver,
range-partition through the shuffle, locally sort each range
(GpuRangePartitioner.scala:42-95 + GpuSortExec). TPU-native: the whole
pipeline is ONE compiled program per chip —

  1. per row, build an order-preserving f64 ROUTING LANE for the primary
     sort key (nulls/NaN mapped to ±inf per the spec's null ordering;
     descending negates; integer→f64 rounding is monotone, so ties can
     only merge onto one chip, never reorder),
  2. every chip samples its lane at fixed stride; one all_gather shares
     the samples; all chips derive IDENTICAL quantile bounds,
  3. rows route via lax.all_to_all (parallel/shuffle._exchange),
  4. each chip runs the full lexicographic local sort
     (ops/sortkeys.sort_with_payloads) on its range.

Chip order == global order: concatenating shard prefixes in device order
yields the sorted relation, with primary-key ties wholly inside one chip
so multi-key lexicographic order holds globally.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.ops import sortkeys
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.parallel.mesh import DATA_AXIS
from spark_rapids_tpu.shims import get_shims

_SAMPLES_PER_CHIP = 64


def _routing_lane(data, validity, dtype: dt.DType, spec: SortKeySpec,
                  live) -> jax.Array:
    """f64 lane whose ascending order == the spec's order. Dead rows to
    +inf (they park on the last chip and die there)."""
    if dtype.is_floating:
        x = sortkeys.canonicalize_floats(data).astype(jnp.float64)
        nanv = jnp.inf if spec.ascending else -jnp.inf
        x = jnp.where(jnp.isnan(x), nanv,
                      x if spec.ascending else -x)
    else:
        x = data.astype(jnp.float64)
        if not spec.ascending:
            x = -x
    if validity is not None:
        nullv = -jnp.inf if spec.nulls_first else jnp.inf
        if not spec.ascending:
            pass  # null placement is absolute, not direction-relative
        x = jnp.where(validity, x, nullv)
    return jnp.where(live, x, jnp.inf)


class DistributedSortStep:
    def __init__(self, mesh, dtypes: Sequence[dt.DType],
                 specs: Sequence[SortKeySpec], axis: str = DATA_AXIS):
        self.mesh = mesh
        self.dtypes = tuple(dtypes)
        self.specs = tuple(specs)
        self.axis = axis
        self.n_dev = mesh.shape[axis]
        self._fn = self._build()

    def _build(self):
        from spark_rapids_tpu.parallel.shuffle import _exchange

        n_dev = self.n_dev
        axis = self.axis
        dtypes = self.dtypes
        specs = self.specs
        k = _SAMPLES_PER_CHIP

        def device_step(datas, valids, n_rows):
            cap = datas[0].shape[0]
            iota = jnp.arange(cap, dtype=jnp.int32)
            live = iota < n_rows[0]
            s0 = specs[0]
            lane = _routing_lane(datas[s0.ordinal], valids[s0.ordinal],
                                 dtypes[s0.ordinal], s0, live)

            # fixed-stride sample of the live prefix; empty slots +inf
            idx = jnp.clip((jnp.arange(k) *
                            jnp.maximum(n_rows[0], 1)) // k, 0, cap - 1)
            samp = jnp.where(jnp.arange(k) < jnp.minimum(n_rows[0], k),
                             jnp.take(lane, idx), jnp.inf)
            allsamp = jax.lax.all_gather(samp, axis).reshape(-1)
            ssorted = jnp.sort(allsamp)
            total_s = allsamp.shape[0]
            # n_dev-1 interior quantile bounds over the finite samples
            nfin = jnp.sum(jnp.isfinite(ssorted)).astype(jnp.int32)
            nfin = jnp.maximum(nfin, 1)
            qpos = jnp.clip(
                (jnp.arange(1, n_dev) * nfin) // n_dev, 0, total_s - 1)
            bounds = jnp.take(ssorted, qpos)

            dest = jnp.searchsorted(bounds, lane,
                                    side="right").astype(jnp.int32)
            dest = jnp.clip(dest, 0, n_dev - 1)
            ex_d, ex_v, total = _exchange(list(datas), list(valids),
                                          dest, live, n_dev, axis)
            # local full lexicographic sort on this chip's range
            cols = list(zip(ex_d, ex_v))
            payloads = list(ex_d) + list(ex_v)
            out = sortkeys.sort_with_payloads(cols, list(dtypes),
                                              list(specs), total,
                                              payloads)
            nc = len(ex_d)
            out_d = list(out[:nc])
            rcap = ex_d[0].shape[0]
            riota = jnp.arange(rcap, dtype=jnp.int32)
            out_v = [v & (riota < total) for v in out[nc:]]
            return out_d, out_v, total.reshape(1)

        n_cols = len(dtypes)
        in_specs = ([P(axis)] * n_cols, [P(axis)] * n_cols, P(axis))
        out_specs = ([P(axis)] * n_cols, [P(axis)] * n_cols, P(axis))
        fn = get_shims().shard_map()(device_step, mesh=self.mesh,
                                     in_specs=in_specs,
                                     out_specs=out_specs)
        return jax.jit(fn)

    def __call__(self, datas, valids, counts):
        """Row-sharded columns in, RANGE-sorted shards out: device d's
        live prefix holds the d-th global range, locally sorted."""
        return self._fn(datas, valids, counts)
