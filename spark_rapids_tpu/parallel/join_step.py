"""Distributed broadcast (dimension) join over a device mesh.

The multi-chip analogue of GpuBroadcastHashJoinExec: the small build side
is replicated to every chip (XLA keeps an unsharded operand resident per
device — the broadcast), the fact side stays row-sharded, and each chip
probes locally inside ONE compiled program. With a unique-key build side
(the dimension-table contract) the output is row-aligned with the stream
side, so the whole step is statically shaped: matches surface as a
live-mask (inner-join semantics compose with the fused-filter groupby
downstream — enrich + aggregate never materializes a compaction).

Probe strategy: sort the build keys once per step (host or device), then
per-chip vectorized binary search — the TPU replacement for cuDF's hash
probe (no device hash tables; sorted search is branch-free and fuses).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from spark_rapids_tpu.shims import get_shims
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.parallel.mesh import DATA_AXIS


class DistributedDimJoinStep:
    """inner join fact (row-sharded) with dim (replicated, unique keys).

    ``__call__(fact_datas, fact_valids, fact_counts, dim_datas,
    dim_valids)`` returns (out_datas, out_valids, live_mask, counts):
    the fact columns followed by the gathered dim payload columns,
    row-aligned with the fact shards; ``live_mask`` marks matched rows.
    """

    def __init__(self, mesh: Mesh, fact_dtypes: Sequence[dt.DType],
                 dim_dtypes: Sequence[dt.DType], fact_key: int,
                 dim_key: int, axis: str = DATA_AXIS):
        self.mesh = mesh
        self.fact_dtypes = tuple(fact_dtypes)
        self.dim_dtypes = tuple(dim_dtypes)
        self.fact_key = fact_key
        self.dim_key = dim_key
        self.axis = axis
        self._fn = self._build()

    def _build(self):
        fact_key = self.fact_key
        dim_key = self.dim_key
        n_fact = len(self.fact_dtypes)
        n_dim = len(self.dim_dtypes)

        def device_step(f_datas, f_valids, f_count, d_datas, d_valids):
            cap = f_datas[0].shape[0]
            live = jnp.arange(cap, dtype=jnp.int32) < f_count[0]
            dcap = d_datas[0].shape[0]
            dkey = d_datas[dim_key]
            dvalid = d_valids[dim_key]
            # sort the dim by key (per device, tiny) for binary search;
            # invalid keys to the back
            order = jnp.lexsort((jnp.arange(dcap), ~dvalid, dkey))
            dkey_s = jnp.take(dkey, order)
            dvalid_s = jnp.take(dvalid, order)
            skey = f_datas[fact_key]
            svalid = f_valids[fact_key]
            pos = jnp.searchsorted(
                jnp.where(dvalid_s, dkey_s,
                          jnp.iinfo(jnp.int64).max
                          if dkey_s.dtype == jnp.int64
                          else dkey_s.max(initial=0) + 1),
                skey)
            posc = jnp.clip(pos, 0, dcap - 1)
            hit = (jnp.take(dkey_s, posc) == skey) & \
                jnp.take(dvalid_s, posc) & svalid & live
            out_d = list(f_datas)
            out_v = list(f_valids)
            src = jnp.take(order, posc)
            for j in range(n_dim):
                if j == dim_key:
                    continue
                out_d.append(jnp.take(d_datas[j], src))
                out_v.append(jnp.take(d_valids[j], src) & hit)
            new_count = jnp.sum(hit).astype(jnp.int32)
            return out_d, out_v, hit, new_count.reshape(1)

        ax = self.axis
        in_specs = ([P(ax)] * n_fact, [P(ax)] * n_fact, P(ax),
                    [P()] * n_dim, [P()] * n_dim)
        n_out = n_fact + n_dim - 1
        out_specs = ([P(ax)] * n_out, [P(ax)] * n_out, P(ax), P(ax))
        fn = get_shims().shard_map()(device_step, mesh=self.mesh,
                       in_specs=in_specs, out_specs=out_specs)
        return jax.jit(fn)

    def __call__(self, fact_datas, fact_valids, fact_counts,
                 dim_datas, dim_valids):
        return self._fn(fact_datas, fact_valids, fact_counts,
                        dim_datas, dim_valids)

    def output_dtypes(self) -> List[dt.DType]:
        out = list(self.fact_dtypes)
        out += [t for j, t in enumerate(self.dim_dtypes)
                if j != self.dim_key]
        return out


class DistributedShuffledJoinStep:
    """Shuffled equi-join over the mesh: BOTH sides hash-route their rows
    by join key through a ``lax.all_to_all`` (the multi-chip analogue of
    the two hash ShuffleExchangeExecs under GpuShuffledHashJoinExec), so
    equal keys co-locate; each chip then probes its local build shard with
    a sorted-hash binary search — all inside ONE compiled program.

    Build-side contract: the ROUTED build shard must have unique join keys
    (the PK/dimension side). Duplicate keys (or hash-collision runs longer
    than ``W``) raise a per-chip ``dup`` flag in the output; the caller
    must then fall back (or flip sides) — results with dup=0 are exact.

    String key columns must ride a dictionary UNIFIED across both sides
    (ops/join.unify_join_strings) so codes are faithful equality images.

    Kinds: inner / left / leftsemi / leftanti. Null join keys never match
    (SQL equi-join semantics; the reference filters them the same way,
    GpuHashJoin.scala:134-193).
    """

    W = 4  # candidate window per probe row (hash-collision tolerance)

    def __init__(self, mesh: Mesh, kind: str,
                 stream_dtypes: Sequence[dt.DType],
                 build_dtypes: Sequence[dt.DType],
                 stream_keys: Sequence[int], build_keys: Sequence[int],
                 axis: str = DATA_AXIS):
        assert kind in ("inner", "left", "leftsemi", "leftanti"), kind
        self.mesh = mesh
        self.kind = kind
        self.stream_dtypes = tuple(stream_dtypes)
        self.build_dtypes = tuple(build_dtypes)
        self.stream_keys = tuple(stream_keys)
        self.build_keys = tuple(build_keys)
        self.axis = axis
        self.n_dev = mesh.shape[axis]
        self._fn = self._build()

    @property
    def emits_build_columns(self) -> bool:
        return self.kind in ("inner", "left")

    def output_dtypes(self) -> List[dt.DType]:
        out = list(self.stream_dtypes)
        if self.emits_build_columns:
            out += list(self.build_dtypes)
        return out

    def _build(self):
        from spark_rapids_tpu.ops import hashing
        from spark_rapids_tpu.parallel.shuffle import _exchange, _key_image

        kind = self.kind
        n_dev = self.n_dev
        axis = self.axis
        sdt, bdt = self.stream_dtypes, self.build_dtypes
        skeys, bkeys = self.stream_keys, self.build_keys
        W = self.W
        emits_build = self.emits_build_columns
        I64MAX = jnp.int64(0x7FFFFFFFFFFFFFFF)

        def device_step(s_datas, s_valids, s_count, b_datas, b_valids,
                        b_count):
            scap = s_datas[0].shape[0]
            bcap = b_datas[0].shape[0]
            s_live = jnp.arange(scap, dtype=jnp.int32) < s_count[0]
            b_live = jnp.arange(bcap, dtype=jnp.int32) < b_count[0]

            def key_parts(datas, valids, ordinals, dtypes):
                imgs = tuple(_key_image(datas[o], valids[o], dtypes[o])
                             for o in ordinals)
                nul = jnp.zeros(datas[0].shape[0], dtype=bool)
                for o in ordinals:
                    nul = nul | ~valids[o]
                return imgs, nul

            s_imgs, s_nul = key_parts(s_datas, s_valids, skeys, sdt)
            b_imgs, b_nul = key_parts(b_datas, b_valids, bkeys, bdt)
            h_s = hashing._combine(s_imgs)
            h_b = hashing._combine(b_imgs)

            def dest_of(h):
                d = (jax.lax.rem(h, jnp.int64(n_dev)) +
                     jnp.int64(n_dev)) % jnp.int64(n_dev)
                return d.astype(jnp.int32)

            ex_s_d, ex_s_v, s_total = _exchange(
                list(s_datas), list(s_valids), dest_of(h_s), s_live,
                n_dev, axis)
            ex_b_d, ex_b_v, b_total = _exchange(
                list(b_datas), list(b_valids), dest_of(h_b), b_live,
                n_dev, axis)

            pcap = ex_s_d[0].shape[0]  # n_dev * scap
            qcap = ex_b_d[0].shape[0]
            p_iota = jnp.arange(pcap, dtype=jnp.int32)
            q_iota = jnp.arange(qcap, dtype=jnp.int32)
            p_live = p_iota < s_total
            q_live = q_iota < b_total

            # recompute key images on the routed shards
            p_imgs, p_nul = key_parts(ex_s_d, ex_s_v, skeys, sdt)
            q_imgs, q_nul = key_parts(ex_b_d, ex_b_v, bkeys, bdt)
            h_p = hashing._combine(p_imgs)
            h_q = hashing._combine(q_imgs)

            # sort the local build shard by hash; dead/null rows park at
            # +inf and carry a usable=False lane so they can never match
            q_use = q_live & ~q_nul
            q_key = jnp.where(q_use, h_q, I64MAX)
            sorted_b = jax.lax.sort(
                (q_key,) + tuple(q_imgs) + tuple(ex_b_d) + tuple(ex_b_v) +
                (q_use,), num_keys=1, is_stable=True)
            bq_key = sorted_b[0]
            nq = len(q_imgs)
            bq_imgs = sorted_b[1:1 + nq]
            nb = len(ex_b_d)
            bq_d = sorted_b[1 + nq:1 + nq + nb]
            bq_v = sorted_b[1 + nq + nb:1 + nq + 2 * nb]
            bq_use = sorted_b[-1]

            p_use = p_live & ~p_nul
            lo = jnp.searchsorted(bq_key, h_p, side="left").astype(jnp.int32)
            hi = jnp.searchsorted(bq_key, h_p, side="right").astype(jnp.int32)

            nmatch = jnp.zeros(pcap, dtype=jnp.int32)
            first_src = jnp.zeros(pcap, dtype=jnp.int32)
            for k in range(W):
                cand = jnp.clip(lo + k, 0, qcap - 1)
                in_run = (lo + k) < hi
                exact = in_run & jnp.take(bq_use, cand) & p_use
                for pi, qi in zip(p_imgs, bq_imgs):
                    exact = exact & (pi == jnp.take(qi, cand))
                first_src = jnp.where(exact & (nmatch == 0), cand,
                                      first_src)
                nmatch = nmatch + exact.astype(jnp.int32)
            hit = nmatch > 0
            # any probe run longer than the window could hide a match past
            # it — flag regardless of hit, or results would be silently
            # wrong, not just non-unique
            dup = jnp.any((nmatch > 1) | (p_use & ((hi - lo) > W)))

            if kind == "inner":
                live_out = hit
            elif kind == "left":
                live_out = p_live
            elif kind == "leftsemi":
                live_out = hit
            else:  # leftanti
                live_out = p_live & ~hit
            out_d = list(ex_s_d)
            out_v = [v & live_out for v in ex_s_v]
            if emits_build:
                for j in range(nb):
                    out_d.append(jnp.take(bq_d[j], first_src))
                    out_v.append(jnp.take(bq_v[j], first_src) & hit &
                                 live_out)
            # compact live rows to a prefix (scatter-free liveness sort)
            total = jnp.sum(live_out).astype(jnp.int32)
            packed = jax.lax.sort(
                ((~live_out).astype(jnp.int32),) + tuple(out_d) +
                tuple(out_v), num_keys=1, is_stable=True)[1:]
            ncols = len(out_d)
            res_d = list(packed[:ncols])
            res_v = [v & (p_iota < total) for v in packed[ncols:]]
            return res_d, res_v, total.reshape(1), dup.reshape(1)

        ax = self.axis
        n_s, n_b = len(sdt), len(bdt)
        n_out = n_s + (n_b if emits_build else 0)
        in_specs = ([P(ax)] * n_s, [P(ax)] * n_s, P(ax),
                    [P(ax)] * n_b, [P(ax)] * n_b, P(ax))
        out_specs = ([P(ax)] * n_out, [P(ax)] * n_out, P(ax), P(ax))
        fn = get_shims().shard_map()(device_step, mesh=self.mesh,
                                     in_specs=in_specs,
                                     out_specs=out_specs)
        return jax.jit(fn)

    def __call__(self, stream_datas, stream_valids, stream_counts,
                 build_datas, build_valids, build_counts):
        """All operands row-sharded ``P(axis)``; counts are per-shard live
        row counts. Returns (out_datas, out_valids, out_counts, dup_flags)
        — dup_flags nonzero on any chip means the unique-build contract
        failed and the result must be discarded."""
        return self._fn(stream_datas, stream_valids, stream_counts,
                        build_datas, build_valids, build_counts)


def replicate_dim(mesh: Mesh, arrays, dtypes, validities=None):
    """Place the dim table unsharded (replicated) on the mesh."""
    sharding = NamedSharding(mesh, P())
    datas, valids = [], []
    vin = validities or [None] * len(arrays)
    for a, t, v in zip(arrays, dtypes, vin):
        datas.append(jax.device_put(
            jnp.asarray(np.asarray(a, dtype=t.np_dtype)), sharding))
        mask = np.ones(len(a), dtype=bool) if v is None else \
            np.asarray(v, dtype=bool)
        valids.append(jax.device_put(jnp.asarray(mask), sharding))
    return datas, valids


class DistributedExpandJoinStep:
    """Shuffled equi-join over the mesh with ARBITRARY fan-out
    (fact x fact): the many-to-many shape the windowed unique-build step
    (DistributedShuffledJoinStep) must dup-flag away. Single join key.

    Both sides route rows by the key's int64 content image (injective —
    not a lossy hash), so per-chip probes are EXACT:

      1. all_to_all route both sides by key image,
      2. sort the local build shard by image: each probe row's match run
         is [searchsorted(left), searchsorted(right)) — exact count, no
         collision window, no dup flag,
      3. inner/left expand: output row j maps back to its probe row via
         one searchsorted over the inclusive-cumsum of match counts,
         then stream/build columns GATHER into a static ``out_cap``
         buffer (the reference's cuDF join also gathers both sides,
         GpuHashJoin.scala:302-318),
      4. semi/anti need no expansion — mask + liveness compaction.

    Output capacity is static; ``overflow`` flags chips whose true join
    size exceeded it — the caller re-plans with a bigger bucket (a
    recompile, bounded by pow2 capacity buckets), never wrong results.
    """

    def __init__(self, mesh: Mesh, kind: str,
                 stream_dtypes: Sequence[dt.DType],
                 build_dtypes: Sequence[dt.DType],
                 stream_key: int, build_key: int, out_cap: int,
                 axis: str = DATA_AXIS):
        assert kind in ("inner", "left", "leftsemi", "leftanti"), kind
        self.mesh = mesh
        self.kind = kind
        self.stream_dtypes = tuple(stream_dtypes)
        self.build_dtypes = tuple(build_dtypes)
        self.stream_key = stream_key
        self.build_key = build_key
        self.out_cap = out_cap
        self.axis = axis
        self.n_dev = mesh.shape[axis]
        self._fn = self._build()

    @property
    def emits_build_columns(self) -> bool:
        return self.kind in ("inner", "left")

    def output_dtypes(self) -> List[dt.DType]:
        out = list(self.stream_dtypes)
        if self.emits_build_columns:
            out += list(self.build_dtypes)
        return out

    def _build(self):
        from spark_rapids_tpu.parallel.shuffle import (_exchange,
                                                       _key_image)

        kind = self.kind
        n_dev = self.n_dev
        axis = self.axis
        sdt, bdt = self.stream_dtypes, self.build_dtypes
        skey_o, bkey_o = self.stream_key, self.build_key
        ocap = self.out_cap
        emits_build = self.emits_build_columns
        I64MAX = jnp.int64(0x7FFFFFFFFFFFFFFF)

        def device_step(s_datas, s_valids, s_count, b_datas, b_valids,
                        b_count):
            scap = s_datas[0].shape[0]
            bcap = b_datas[0].shape[0]
            s_live = jnp.arange(scap, dtype=jnp.int32) < s_count[0]
            b_live = jnp.arange(bcap, dtype=jnp.int32) < b_count[0]
            s_img = _key_image(s_datas[skey_o], s_valids[skey_o],
                               sdt[skey_o])
            b_img = _key_image(b_datas[bkey_o], b_valids[bkey_o],
                               bdt[bkey_o])

            def dest_of(img):
                d = (jax.lax.rem(img, jnp.int64(n_dev)) +
                     jnp.int64(n_dev)) % jnp.int64(n_dev)
                return d.astype(jnp.int32)

            ex_s_d, ex_s_v, s_total = _exchange(
                list(s_datas), list(s_valids), dest_of(s_img), s_live,
                n_dev, axis)
            ex_b_d, ex_b_v, b_total = _exchange(
                list(b_datas), list(b_valids), dest_of(b_img), b_live,
                n_dev, axis)

            pcap = ex_s_d[0].shape[0]
            qcap = ex_b_d[0].shape[0]
            p_iota = jnp.arange(pcap, dtype=jnp.int32)
            q_iota = jnp.arange(qcap, dtype=jnp.int32)
            p_live = p_iota < s_total
            q_live = q_iota < b_total

            p_img = _key_image(ex_s_d[skey_o], ex_s_v[skey_o],
                               sdt[skey_o])
            q_img = _key_image(ex_b_d[bkey_o], ex_b_v[bkey_o],
                               bdt[bkey_o])
            p_use = p_live & ex_s_v[skey_o]
            q_use = q_live & ex_b_v[bkey_o]

            # sort local build: USABLE rows first (by exact key image),
            # dead/null rows after. The usable rows form a prefix, so
            # clamping [lo, hi) to it makes sentinel collisions
            # impossible — a live key equal to I64MAX can never match a
            # dead row (r3 review finding)
            use_rank = (~q_use).astype(jnp.int32)
            q_key = jnp.where(q_use, q_img, I64MAX)
            sorted_b = jax.lax.sort(
                (use_rank, q_key) + tuple(ex_b_d) + tuple(ex_b_v),
                num_keys=2, is_stable=True)
            bq_key = sorted_b[1]
            nb = len(ex_b_d)
            bq_d = sorted_b[2:2 + nb]
            bq_v = sorted_b[2 + nb:]
            n_usable = jnp.sum(q_use).astype(jnp.int32)

            probe = jnp.where(p_use, p_img, I64MAX)
            lo = jnp.searchsorted(bq_key, probe,
                                  side="left").astype(jnp.int32)
            hi = jnp.searchsorted(bq_key, probe,
                                  side="right").astype(jnp.int32)
            lo = jnp.minimum(lo, n_usable)
            hi = jnp.minimum(hi, n_usable)
            nmatch = jnp.where(p_use, hi - lo, 0)
            hit = nmatch > 0

            if kind in ("leftsemi", "leftanti"):
                live_out = (hit if kind == "leftsemi"
                            else p_live & ~hit)
                total = jnp.sum(live_out).astype(jnp.int32)
                packed = jax.lax.sort(
                    ((~live_out).astype(jnp.int32),) + tuple(ex_s_d) +
                    tuple(ex_s_v), num_keys=1, is_stable=True)[1:]
                ns = len(ex_s_d)
                res_d = list(packed[:ns])
                res_v = [v & (p_iota < total) for v in packed[ns:]]
                return (res_d, res_v, total.reshape(1),
                        total.astype(jnp.int64).reshape(1))

            # inner/left expansion. int64 accumulation: a hot key can
            # expand past 2^31 rows per chip — int32 would wrap the
            # total negative and mask the overflow flag (r3 review)
            emit = nmatch if kind == "inner" else \
                jnp.where(p_live, jnp.maximum(nmatch, 1), 0)
            csum = jnp.cumsum(emit.astype(jnp.int64))
            total = csum[-1]  # TRUE size, returned so the caller can
            # size the retry bucket exactly on overflow
            j = jnp.arange(ocap, dtype=jnp.int64)
            p_of = jnp.searchsorted(csum, j,
                                    side="right").astype(jnp.int32)
            p_of = jnp.clip(p_of, 0, pcap - 1)
            start = (jnp.take(csum, p_of) -
                     jnp.take(emit, p_of).astype(jnp.int64))
            off = (j - start).astype(jnp.int32)
            jlive = j < jnp.minimum(total, jnp.int64(ocap))
            j = j.astype(jnp.int32)
            b_of = jnp.clip(jnp.take(lo, p_of) + off, 0, qcap - 1)
            matched = jnp.take(hit, p_of) & jlive
            out_d = [jnp.take(d, p_of) for d in ex_s_d]
            out_v = [jnp.take(v, p_of) & jlive for v in ex_s_v]
            for jb in range(nb):
                out_d.append(jnp.take(bq_d[jb], b_of))
                out_v.append(jnp.take(bq_v[jb], b_of) & matched)
            return (out_d, out_v,
                    jnp.minimum(total,
                                jnp.int64(ocap)).astype(jnp.int32)
                    .reshape(1),
                    total.reshape(1))

        ax = self.axis
        n_s, n_b = len(sdt), len(bdt)
        n_out = n_s + (n_b if emits_build else 0)
        in_specs = ([P(ax)] * n_s, [P(ax)] * n_s, P(ax),
                    [P(ax)] * n_b, [P(ax)] * n_b, P(ax))
        out_specs = ([P(ax)] * n_out, [P(ax)] * n_out, P(ax), P(ax))
        fn = get_shims().shard_map()(device_step, mesh=self.mesh,
                                     in_specs=in_specs,
                                     out_specs=out_specs)
        return jax.jit(fn)

    def __call__(self, stream_datas, stream_valids, stream_counts,
                 build_datas, build_valids, build_counts):
        """Returns (out_datas, out_valids, out_counts, true_totals);
        per-chip true_totals (int64, UNclamped) above out_cap mean the
        static bucket was too small — the caller rebuilds with
        bucket_capacity(max(true_totals)) and reruns, so one retry
        always suffices."""
        return self._fn(stream_datas, stream_valids, stream_counts,
                        build_datas, build_valids, build_counts)


class DistributedNullExtendUnionStep:
    """Per-chip union of the two FULL OUTER halves, entirely sharded.

    The left half carries the full (left + right) output schema (a LEFT
    join's rows); the anti half carries only the right-side columns (the
    unmatched right rows). Each chip appends the anti half's live prefix
    after the left half's, synthesizing all-null left columns for the
    appended rows — no ``all_to_all``, no host gather. This keeps the
    round-3 sharded hand-off contract: a chained mesh parent consumes
    the unioned result without ever leaving the devices (the reference
    emits both halves from one kernel, GpuHashJoin.scala:302-318; here
    the halves are separate programs so the union is its own tiny one).

    Output capacity is static per (left-cap, anti-cap) shape pair and
    always sufficient: out_cap = bucket_capacity(lcap + acap) bounds
    every per-chip row count by construction, so no overflow flag.
    """

    def __init__(self, mesh: Mesh, left_dtypes: Sequence[dt.DType],
                 right_dtypes: Sequence[dt.DType], axis: str = DATA_AXIS):
        self.mesh = mesh
        self.left_dtypes = tuple(left_dtypes)
        self.right_dtypes = tuple(right_dtypes)
        self.axis = axis
        self._fn = self._build()

    def output_dtypes(self) -> List[dt.DType]:
        return list(self.left_dtypes) + list(self.right_dtypes)

    def _build(self):
        from spark_rapids_tpu.ops.buckets import bucket_capacity

        n_left = len(self.left_dtypes)
        n_right = len(self.right_dtypes)

        def device_step(a_datas, a_valids, a_count, b_datas, b_valids,
                        b_count):
            acap = a_datas[0].shape[0]
            bcap = b_datas[0].shape[0]
            # shapes are static at trace time, so the output bucket is too
            ocap = bucket_capacity(acap + bcap)
            c1 = a_count[0]
            c2 = b_count[0]
            j = jnp.arange(ocap, dtype=jnp.int32)
            from_a = j < c1
            ai = jnp.clip(j, 0, acap - 1)
            bi = jnp.clip(j - c1, 0, bcap - 1)
            live = j < (c1 + c2)
            out_d, out_v = [], []
            for i in range(n_left):
                # left columns: the anti half contributes NULLs
                da = jnp.take(a_datas[i], ai)
                out_d.append(jnp.where(from_a, da,
                                       jnp.zeros((), da.dtype)))
                out_v.append(jnp.where(from_a,
                                       jnp.take(a_valids[i], ai),
                                       False) & live)
            for i in range(n_right):
                out_d.append(jnp.where(
                    from_a, jnp.take(a_datas[n_left + i], ai),
                    jnp.take(b_datas[i], bi)))
                out_v.append(jnp.where(
                    from_a, jnp.take(a_valids[n_left + i], ai),
                    jnp.take(b_valids[i], bi)) & live)
            return out_d, out_v, (c1 + c2).reshape(1)

        ax = self.axis
        n_a = n_left + n_right
        n_out = n_left + n_right
        in_specs = ([P(ax)] * n_a, [P(ax)] * n_a, P(ax),
                    [P(ax)] * n_right, [P(ax)] * n_right, P(ax))
        out_specs = ([P(ax)] * n_out, [P(ax)] * n_out, P(ax))
        fn = get_shims().shard_map()(device_step, mesh=self.mesh,
                                     in_specs=in_specs,
                                     out_specs=out_specs)
        return jax.jit(fn)

    def __call__(self, left_datas, left_valids, left_counts,
                 anti_datas, anti_valids, anti_counts):
        """left_* carry (n_left + n_right) columns; anti_* carry n_right.
        Returns (out_datas, out_valids, out_counts) sharded ``P(axis)``."""
        return self._fn(left_datas, left_valids, left_counts,
                        anti_datas, anti_valids, anti_counts)
