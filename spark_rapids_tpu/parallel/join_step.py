"""Distributed broadcast (dimension) join over a device mesh.

The multi-chip analogue of GpuBroadcastHashJoinExec: the small build side
is replicated to every chip (XLA keeps an unsharded operand resident per
device — the broadcast), the fact side stays row-sharded, and each chip
probes locally inside ONE compiled program. With a unique-key build side
(the dimension-table contract) the output is row-aligned with the stream
side, so the whole step is statically shaped: matches surface as a
live-mask (inner-join semantics compose with the fused-filter groupby
downstream — enrich + aggregate never materializes a compaction).

Probe strategy: sort the build keys once per step (host or device), then
per-chip vectorized binary search — the TPU replacement for cuDF's hash
probe (no device hash tables; sorted search is branch-free and fuses).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from spark_rapids_tpu.shims import get_shims
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.parallel.mesh import DATA_AXIS


class DistributedDimJoinStep:
    """inner join fact (row-sharded) with dim (replicated, unique keys).

    ``__call__(fact_datas, fact_valids, fact_counts, dim_datas,
    dim_valids)`` returns (out_datas, out_valids, live_mask, counts):
    the fact columns followed by the gathered dim payload columns,
    row-aligned with the fact shards; ``live_mask`` marks matched rows.
    """

    def __init__(self, mesh: Mesh, fact_dtypes: Sequence[dt.DType],
                 dim_dtypes: Sequence[dt.DType], fact_key: int,
                 dim_key: int, axis: str = DATA_AXIS):
        self.mesh = mesh
        self.fact_dtypes = tuple(fact_dtypes)
        self.dim_dtypes = tuple(dim_dtypes)
        self.fact_key = fact_key
        self.dim_key = dim_key
        self.axis = axis
        self._fn = self._build()

    def _build(self):
        fact_key = self.fact_key
        dim_key = self.dim_key
        n_fact = len(self.fact_dtypes)
        n_dim = len(self.dim_dtypes)

        def device_step(f_datas, f_valids, f_count, d_datas, d_valids):
            cap = f_datas[0].shape[0]
            live = jnp.arange(cap, dtype=jnp.int32) < f_count[0]
            dcap = d_datas[0].shape[0]
            dkey = d_datas[dim_key]
            dvalid = d_valids[dim_key]
            # sort the dim by key (per device, tiny) for binary search;
            # invalid keys to the back
            order = jnp.lexsort((jnp.arange(dcap), ~dvalid, dkey))
            dkey_s = jnp.take(dkey, order)
            dvalid_s = jnp.take(dvalid, order)
            skey = f_datas[fact_key]
            svalid = f_valids[fact_key]
            pos = jnp.searchsorted(
                jnp.where(dvalid_s, dkey_s,
                          jnp.iinfo(jnp.int64).max
                          if dkey_s.dtype == jnp.int64
                          else dkey_s.max(initial=0) + 1),
                skey)
            posc = jnp.clip(pos, 0, dcap - 1)
            hit = (jnp.take(dkey_s, posc) == skey) & \
                jnp.take(dvalid_s, posc) & svalid & live
            out_d = list(f_datas)
            out_v = list(f_valids)
            src = jnp.take(order, posc)
            for j in range(n_dim):
                if j == dim_key:
                    continue
                out_d.append(jnp.take(d_datas[j], src))
                out_v.append(jnp.take(d_valids[j], src) & hit)
            new_count = jnp.sum(hit).astype(jnp.int32)
            return out_d, out_v, hit, new_count.reshape(1)

        ax = self.axis
        in_specs = ([P(ax)] * n_fact, [P(ax)] * n_fact, P(ax),
                    [P()] * n_dim, [P()] * n_dim)
        n_out = n_fact + n_dim - 1
        out_specs = ([P(ax)] * n_out, [P(ax)] * n_out, P(ax), P(ax))
        fn = get_shims().shard_map()(device_step, mesh=self.mesh,
                       in_specs=in_specs, out_specs=out_specs)
        return jax.jit(fn)

    def __call__(self, fact_datas, fact_valids, fact_counts,
                 dim_datas, dim_valids):
        return self._fn(fact_datas, fact_valids, fact_counts,
                        dim_datas, dim_valids)

    def output_dtypes(self) -> List[dt.DType]:
        out = list(self.fact_dtypes)
        out += [t for j, t in enumerate(self.dim_dtypes)
                if j != self.dim_key]
        return out


def replicate_dim(mesh: Mesh, arrays, dtypes, validities=None):
    """Place the dim table unsharded (replicated) on the mesh."""
    sharding = NamedSharding(mesh, P())
    datas, valids = [], []
    vin = validities or [None] * len(arrays)
    for a, t, v in zip(arrays, dtypes, vin):
        datas.append(jax.device_put(
            jnp.asarray(np.asarray(a, dtype=t.np_dtype)), sharding))
        mask = np.ones(len(a), dtype=bool) if v is None else \
            np.asarray(v, dtype=bool)
        valids.append(jax.device_put(jnp.asarray(mask), sharding))
    return datas, valids
