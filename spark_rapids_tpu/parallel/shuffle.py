"""Distributed row exchange + aggregation over a device mesh.

The TPU-native re-imagining of the reference's GPU shuffle (SURVEY.md §2.8):
GpuShuffleExchangeExec partitions batches on device and hands the pieces to
a UCX transport that tag-routes them between executor GPUs
(GpuShuffleExchangeExec.scala:146-248; shuffle-plugin/.../UCX.scala). Here
every chip is a position on a ``jax.sharding.Mesh``; the whole exchange is
ONE compiled program per chip:

  1. per-device: hash the key columns → destination device per row,
  2. sort rows by destination (the contiguous-split trick the reference
     does with ``Table.partition``, GpuPartitioning.scala:44-70),
  3. scatter into fixed (n_dev, capacity) send blocks,
  4. ``jax.lax.all_to_all`` the blocks + per-destination counts — XLA lowers
     this onto ICI links directly (no bounce buffers, no progress thread),
  5. compact received rows to a live prefix and run the local sort-based
     groupby kernel (ops/groupby.py) on them.

Because keys are hash-routed, each device ends up owning a disjoint key
space — the distributed aggregate is exact with no final merge step (the
reference needs a second shuffle stage for the same guarantee).

Dynamic-size note: counts ride as data through the same all_to_all, so the
entire step stays statically shaped; only materialization realizes counts.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu.shims import get_shims

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.ops import groupby as gb
from spark_rapids_tpu.ops import hashing
from spark_rapids_tpu.parallel.mesh import DATA_AXIS


def _key_image(data: jax.Array, validity: jax.Array,
               dtype: dt.DType) -> jax.Array:
    """int64 hashable image per row; nulls collapse to one image.
    STRING columns must already be on a mesh-wide unified dictionary, so
    their codes are a faithful content image."""
    if dtype is dt.STRING:
        img = data.astype(jnp.int64)
    else:
        img = hashing._numeric_to_int64(data, dtype)
    return jnp.where(validity, img, jnp.int64(-0x61C8864680B583EB))


def _exchange(datas: List[jax.Array], valids: List[jax.Array],
              dest: jax.Array, live: jax.Array, n_dev: int, axis: str
              ) -> Tuple[List[jax.Array], List[jax.Array], jax.Array]:
    """All-to-all rows by per-row destination device. Returns compacted
    (datas, valids, total_rows) with capacity n_dev * local_capacity.

    Scatter-free: ONE variadic sort carries every column to
    destination-sorted order (padding to a sentinel bucket), per-dest
    counts come from binary searches over the sorted destinations, and
    the (n_dev, cap) send blocks are a plain gather from the contiguous
    runs — TPU scatters measured ~30x a cumsum, so none appear here."""
    cap = dest.shape[0]
    dest_l = jnp.where(live, dest, n_dev)  # padding → sentinel bucket
    payloads = tuple(datas) + tuple(valids)
    sorted_all = jax.lax.sort((dest_l,) + payloads, num_keys=1,
                              is_stable=True)
    dest_s = sorted_all[0]
    datas_s = sorted_all[1:1 + len(datas)]
    valids_s = sorted_all[1 + len(datas):]

    bounds = jnp.searchsorted(
        dest_s, jnp.arange(n_dev + 1, dtype=dest_s.dtype)).astype(jnp.int32)
    counts = bounds[1:] - bounds[:-1]
    start = bounds[:-1]

    k = jnp.arange(n_dev * cap, dtype=jnp.int32)
    d_of = k // cap
    j_of = k % cap
    src = jnp.clip(jnp.take(start, d_of) + j_of, 0, cap - 1)
    sel = j_of < jnp.take(counts, d_of)

    def to_blocks(x):
        vals = jnp.where(sel, jnp.take(x, src), jnp.zeros((), x.dtype))
        return vals.reshape(n_dev, cap)

    recv_d = [jax.lax.all_to_all(to_blocks(d), axis, 0, 0)
              for d in datas_s]
    recv_v = [jax.lax.all_to_all(to_blocks(v), axis, 0, 0)
              for v in valids_s]
    counts_recv = jax.lax.all_to_all(
        counts.reshape(n_dev, 1), axis, 0, 0).reshape(n_dev)

    # compact received rows to a live prefix: one more variadic sort
    # keyed on liveness, carrying every received column
    rcap = n_dev * cap
    riota = jnp.arange(rcap, dtype=jnp.int32)
    live_r = (riota % cap) < jnp.take(counts_recv, riota // cap)
    total = jnp.sum(counts_recv).astype(jnp.int32)
    flat = tuple(r.reshape(rcap) for r in recv_d) + \
        tuple(r.reshape(rcap) for r in recv_v)
    packed = jax.lax.sort(((~live_r).astype(jnp.int32),) + flat,
                          num_keys=1, is_stable=True)[1:]
    out_d = list(packed[:len(recv_d)])
    out_v = [v & (riota < total) for v in packed[len(recv_d):]]
    return out_d, out_v, total


class DistributedGroupByStep:
    """Compiled multi-chip groupby-aggregate: shard rows → hash-route →
    all_to_all → per-device sort-based aggregation. The flagship distributed
    pipeline (shuffle exchange + hash aggregate fused into one program)."""

    def __init__(self, mesh: Mesh, dtypes: Sequence[dt.DType],
                 key_ordinals: Sequence[int], aggs: Sequence[gb.AggSpec],
                 axis: str = DATA_AXIS):
        self.mesh = mesh
        self.dtypes = tuple(dtypes)
        self.key_ordinals = tuple(key_ordinals)
        self.aggs = tuple(aggs)
        self.axis = axis
        self.n_dev = mesh.shape[axis]
        self._fn = self._build()

    def _build(self):
        n_dev = self.n_dev
        dtypes = self.dtypes
        key_ordinals = self.key_ordinals
        aggs = self.aggs
        axis = self.axis

        def device_step(datas, valids, n_rows):
            # block shapes: datas[i] (cap,), n_rows (1,)
            cap = datas[0].shape[0]
            live = jnp.arange(cap, dtype=jnp.int32) < n_rows[0]
            imgs = tuple(
                _key_image(datas[o], valids[o], dtypes[o])
                for o in key_ordinals)
            h = hashing._combine(imgs)
            dest = (jax.lax.rem(h, jnp.int64(n_dev)) +
                    jnp.int64(n_dev)) % jnp.int64(n_dev)
            dest = dest.astype(jnp.int32)
            ex_d, ex_v, total = _exchange(list(datas), list(valids), dest,
                                          live, n_dev, axis)
            cols = [(d, v) for d, v in zip(ex_d, ex_v)]
            (key_d, key_v), (agg_d, agg_v), ng = gb._groupby(
                cols, dtypes, key_ordinals, aggs, total)
            rcap = n_dev * cap
            ones = jnp.ones(rcap, dtype=bool)
            out_d = list(key_d) + list(agg_d)
            out_v = [ones if v is None else v for v in key_v] + \
                    [ones if v is None else v for v in agg_v]
            return out_d, out_v, ng.reshape(1)

        n_cols = len(self.dtypes)
        n_out = len(self.key_ordinals) + len(self.aggs)
        in_specs = ([P(self.axis)] * n_cols, [P(self.axis)] * n_cols,
                    P(self.axis))
        out_specs = ([P(self.axis)] * n_out, [P(self.axis)] * n_out,
                     P(self.axis))
        fn = get_shims().shard_map()(device_step, mesh=self.mesh,
                                     in_specs=in_specs,
                                     out_specs=out_specs)
        return jax.jit(fn)

    def __call__(self, datas: List[jax.Array], valids: List[jax.Array],
                 counts: jax.Array):
        """datas[i]: (n_dev*cap,) row-sharded; counts: (n_dev,) per-shard
        live row counts. Returns (out_datas, out_valids, group_counts)."""
        return self._fn(datas, valids, counts)

    # -- result typing ----------------------------------------------------

    def output_dtypes(self) -> List[dt.DType]:
        out = [self.dtypes[o] for o in self.key_ordinals]
        out += [gb.agg_result_dtype(s, list(self.dtypes)) for s in self.aggs]
        return out


class DistributedShuffleStep:
    """Compiled in-program exchange: hash-route rows by key columns →
    ``lax.all_to_all`` → per-device compacted rows. The transport half
    of :class:`DistributedGroupByStep` without the aggregate tail —
    ``ShuffleExchangeExec``'s in-program mode and the shuffle bench's
    TCP-vs-ICI head-to-head ride this.

    Partition ids are computed EXACTLY like the host partition kernel
    (ops/hashing.hash_columns images incl. the null seed, then pmod by
    ``num_out``), and each row's pid travels through the collective as
    an extra routed column: device ``d`` receives every row whose
    ``pid % n_dev == d`` and the caller splits by pid host-side. That
    identity makes an in-program exchange partition-for-partition
    interchangeable with a host-path one — a co-partitioned sibling
    under a shuffled join may stay on the host path and still line up.

    ``salt_pids`` (AQE replan rule 1, the in-program half): partition
    ids in this tuple are SKEWED — their rows fan out round-robin by
    row position over ALL devices instead of landing on ``pid % n_dev``,
    so one hot key stops making a single chip's receive the straggler
    of the collective. Pids are untouched (only ``dest`` changes); the
    caller's pid-keyed split reassembles full partitions host-side, so
    downstream consumers — including the co-partitioned join contract —
    see identical partition contents, just sourced from several
    devices' blocks.
    """

    def __init__(self, mesh: Mesh, dtypes: Sequence[dt.DType],
                 key_ordinals: Sequence[int], num_out: int,
                 axis: str = DATA_AXIS,
                 salt_pids: Sequence[int] = ()):
        self.mesh = mesh
        self.dtypes = tuple(dtypes)
        self.key_ordinals = tuple(key_ordinals)
        self.num_out = num_out
        self.axis = axis
        self.salt_pids = tuple(sorted(salt_pids))
        self.n_dev = mesh.shape[axis]
        self._fn = self._build()

    def _build(self):
        n_dev = self.n_dev
        num_out = self.num_out
        dtypes = self.dtypes
        key_ordinals = self.key_ordinals
        axis = self.axis
        salt_pids = self.salt_pids

        def device_step(datas, valids, n_rows):
            cap = datas[0].shape[0]
            live = jnp.arange(cap, dtype=jnp.int32) < n_rows[0]
            # host-hash-matching images: _numeric_to_int64 + the null
            # seed hash_columns uses (NOT _key_image's sentinel) so pid
            # here == pid from ops/partition.hash_partition
            imgs = tuple(
                jnp.where(valids[o],
                          hashing._numeric_to_int64(datas[o], dtypes[o]),
                          jnp.int64(hashing._NULL_HASH))
                for o in key_ordinals)
            h = hashing._combine(imgs)
            m = h % jnp.int64(num_out)
            pid = jnp.where(m < 0, m + num_out, m).astype(jnp.int32)
            dest = pid % n_dev
            if salt_pids:
                hot = pid == jnp.int32(salt_pids[0])
                for p in salt_pids[1:]:
                    hot = hot | (pid == jnp.int32(p))
                iota = jnp.arange(cap, dtype=jnp.int32)
                dest = jnp.where(hot, (pid + iota) % n_dev, dest)
            ex = _exchange(list(datas) + [pid.astype(jnp.int64)],
                           list(valids) + [live],
                           dest, live, n_dev, axis)
            ex_d, ex_v, total = ex
            return (ex_d[:-1], ex_v[:-1], ex_d[-1].astype(jnp.int32),
                    total.reshape(1))

        n_cols = len(self.dtypes)
        in_specs = ([P(self.axis)] * n_cols, [P(self.axis)] * n_cols,
                    P(self.axis))
        out_specs = ([P(self.axis)] * n_cols, [P(self.axis)] * n_cols,
                     P(self.axis), P(self.axis))
        return get_shims().shard_map()(device_step, mesh=self.mesh,
                                       in_specs=in_specs,
                                       out_specs=out_specs)

    def __call__(self, datas: List[jax.Array], valids: List[jax.Array],
                 counts: jax.Array):
        """datas[i]: (n_dev*cap,) row-sharded; counts: (n_dev,). Returns
        (out_datas, out_valids, pids, recv_counts): per-device capacity
        n_dev*cap, recv_counts[d] live rows on device d, pids[j] the
        output partition of row j (only pids with pid % n_dev == d land
        on device d)."""
        return _run_shuffle_step(self, list(datas), list(valids), counts)


@partial(jax.jit, static_argnames=("step",))
def _run_shuffle_step(step, datas, valids, counts):
    """ONE module-level jit entry for every shuffle step (the
    execs/interop.py memoized idiom): the trace cache lives here, keyed
    by the identity-stable ``step`` (static) + operand shapes, so a
    fresh wrapper is never minted per call."""
    return step._fn(datas, valids, counts)


# one step per (mesh, schema, keys, parts): identity-stable steps keep
# the shard_map/jit caches warm across repeated exchanges of the same
# plan shape (the progcache in-process layer for sharded programs)
_SHUFFLE_STEPS: dict = {}


def shuffle_step(mesh: Mesh, dtypes: Sequence[dt.DType],
                 key_ordinals: Sequence[int], num_out: int,
                 salt_pids: Sequence[int] = ()) -> DistributedShuffleStep:
    key = (id(mesh), tuple(dtypes), tuple(key_ordinals), num_out,
           tuple(sorted(salt_pids)))
    got = _SHUFFLE_STEPS.get(key)
    if got is None:
        if len(_SHUFFLE_STEPS) >= 64:  # bound: distinct schemas are few
            _SHUFFLE_STEPS.clear()
        got = _SHUFFLE_STEPS[key] = DistributedShuffleStep(
            mesh, dtypes, key_ordinals, num_out, salt_pids=salt_pids)
    return got


def distributed_batch_from_host(mesh: Mesh, arrays: List[np.ndarray],
                                dtypes: List[dt.DType],
                                validities: Optional[List[Optional[np.ndarray]]] = None,
                                axis: str = DATA_AXIS):
    """Shard host rows round-robin-contiguously over the mesh: returns
    (datas, valids, counts) global device arrays with every column
    row-sharded ``P(axis)`` (the reference's RDD partitioning step)."""
    from spark_rapids_tpu.ops.buckets import bucket_capacity

    n_dev = mesh.shape[axis]
    n = len(arrays[0])
    per = -(-n // n_dev)
    cap = bucket_capacity(max(per, 1))
    sharding = NamedSharding(mesh, P(axis))
    datas, valids = [], []
    counts = np.zeros(n_dev, dtype=np.int32)
    for d in range(n_dev):
        lo = min(d * per, n)
        counts[d] = min(per, n - lo) if lo < n else 0
    for a, t in zip(arrays, dtypes):
        buf = np.zeros(n_dev * cap, dtype=t.np_dtype)
        for d in range(n_dev):
            lo = d * per
            seg = a[lo:lo + counts[d]]
            buf[d * cap:d * cap + len(seg)] = seg
        datas.append(jax.device_put(jnp.asarray(buf), sharding))
    vin = validities or [None] * len(arrays)
    for a, v in zip(arrays, vin):
        buf = np.zeros(n_dev * cap, dtype=bool)
        for d in range(n_dev):
            lo = d * per
            c = counts[d]
            buf[d * cap:d * cap + c] = True if v is None else v[lo:lo + c]
        valids.append(jax.device_put(jnp.asarray(buf), sharding))
    counts_dev = jax.device_put(jnp.asarray(counts),
                                NamedSharding(mesh, P(axis)))
    return datas, valids, counts_dev, cap


def gather_distributed_result(out_datas, out_valids, group_counts,
                              dtypes: List[dt.DType], n_dev: int
                              ) -> ColumnarBatch:
    """Collect each device's group prefix to one host-side batch (only for
    result materialization / tests — production consumers keep it sharded)."""
    host_d = [np.asarray(jax.device_get(d)) for d in out_datas]
    host_v = [np.asarray(jax.device_get(v)) for v in out_valids]
    ng = np.asarray(jax.device_get(group_counts))
    rcap = len(host_d[0]) // n_dev
    parts_d = [[] for _ in host_d]
    parts_v = [[] for _ in host_d]
    for dev in range(n_dev):
        k = int(ng[dev])
        for i in range(len(host_d)):
            parts_d[i].append(host_d[i][dev * rcap:dev * rcap + k])
            parts_v[i].append(host_v[i][dev * rcap:dev * rcap + k])
    total = int(ng.sum())
    from spark_rapids_tpu.ops.buckets import bucket_capacity

    cap = bucket_capacity(max(total, 1))
    cols = []
    for i, t in enumerate(dtypes):
        vals = np.concatenate(parts_d[i]) if total else \
            np.zeros(0, dtype=t.np_dtype)
        mask = np.concatenate(parts_v[i]) if total else np.zeros(0, bool)
        cols.append(Column.from_numpy(vals, t, validity=mask, capacity=cap))
    return ColumnarBatch(cols, total)
