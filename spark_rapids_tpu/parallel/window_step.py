"""Distributed window functions over the device mesh.

The reference runs windows per shuffle partition on-device
(GpuWindowExec.scala:92: partition-by keys hash-exchange upstream, then
each GPU batch computes its partitions' windows). The TPU shape fuses
those two stages into ONE compiled program per chip, exactly like the
distributed groupby (parallel/shuffle.py):

  1. hash the PARTITION BY columns -> destination chip per row,
  2. ``lax.all_to_all`` the rows (scatter-free: one variadic sort into
     send blocks),
  3. per chip: one variadic sort by (partition keys, order keys), then
     the same segmented-scan ``WindowKernel`` the single-device exec
     runs (execs/window.py) — row_number/rank/lead/lag/frames all ride
     segment arithmetic, so the per-chip math is identical.

Hash routing puts every row of a partition-by group on one chip, so the
distributed result is exact with no merge stage. Rows come back grouped
by partition-key hash, not globally ordered — same contract as the
reference's post-shuffle window output.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.execs.window import WindowCall, WindowKernel
from spark_rapids_tpu.ops import hashing, sortkeys
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.parallel.mesh import DATA_AXIS
from spark_rapids_tpu.parallel.shuffle import _exchange, _key_image
from spark_rapids_tpu.shims import get_shims


class DistributedWindowStep:
    """Compiled multi-chip window: route by partition keys, per-chip
    sort + segmented window kernel. Output columns are the child
    columns followed by one column per call; per-chip live counts ride
    back sharded."""

    def __init__(self, mesh: Mesh, pre_types: Sequence[dt.DType],
                 partition_ordinals: Sequence[int],
                 order_specs: Sequence[SortKeySpec],
                 calls: Sequence[WindowCall],
                 input_ordinals: Sequence[int], n_child: int,
                 axis: str = DATA_AXIS):
        assert partition_ordinals, \
            "un-partitioned windows are single-device by construction"
        self.mesh = mesh
        self.pre_types = tuple(pre_types)
        self.partition_ordinals = tuple(partition_ordinals)
        self.order_specs = tuple(order_specs)
        self.calls = tuple(calls)
        self.input_ordinals = tuple(input_ordinals)
        self.n_child = n_child
        self.axis = axis
        self.n_dev = mesh.shape[axis]
        self.kernel = WindowKernel(list(pre_types),
                                   list(partition_ordinals),
                                   list(order_specs), list(calls),
                                   list(input_ordinals))
        self._fn = self._build()

    def _build(self):
        n_dev = self.n_dev
        pre_types = self.pre_types
        part_ords = self.partition_ordinals
        axis = self.axis
        sort_specs = tuple(SortKeySpec(o, True, True)
                           for o in part_ords) + self.order_specs
        kernel = self.kernel
        n_child = self.n_child

        def device_step(datas, valids, n_rows):
            cap = datas[0].shape[0]
            live = jnp.arange(cap, dtype=jnp.int32) < n_rows[0]
            imgs = tuple(_key_image(datas[o], valids[o], pre_types[o])
                         for o in part_ords)
            h = hashing._combine(imgs)
            dest = ((jax.lax.rem(h, jnp.int64(n_dev)) + jnp.int64(n_dev))
                    % jnp.int64(n_dev)).astype(jnp.int32)
            ex_d, ex_v, total = _exchange(list(datas), list(valids), dest,
                                          live, n_dev, axis)
            sorted_all = sortkeys.sort_with_payloads(
                list(zip(ex_d, ex_v)), list(pre_types), list(sort_specs),
                total, list(ex_d) + list(ex_v))
            ncols = len(ex_d)
            cols = [Column(t, d, v) for t, d, v in
                    zip(pre_types, sorted_all[:ncols],
                        sorted_all[ncols:])]
            call_cols = kernel(cols, total)
            out_cols = cols[:n_child] + call_cols
            rcap = n_dev * cap
            live_out = jnp.arange(rcap, dtype=jnp.int32) < total
            out_d = [c.data for c in out_cols]
            out_v = [c.validity_or_true() & live_out for c in out_cols]
            return out_d, out_v, total.reshape(1)

        n_cols = len(self.pre_types)
        n_out = self.n_child + len(self.calls)
        in_specs = ([P(self.axis)] * n_cols, [P(self.axis)] * n_cols,
                    P(self.axis))
        out_specs = ([P(self.axis)] * n_out, [P(self.axis)] * n_out,
                     P(self.axis))
        fn = get_shims().shard_map()(device_step, mesh=self.mesh,
                                     in_specs=in_specs,
                                     out_specs=out_specs)
        return jax.jit(fn)

    def __call__(self, datas: List[jax.Array], valids: List[jax.Array],
                 counts: jax.Array):
        """datas[i]: (n_dev*cap,) row-sharded pre-projected columns.
        Returns (out_datas, out_valids, per_chip_counts)."""
        return self._fn(datas, valids, counts)

    def output_dtypes(self) -> List[dt.DType]:
        out = list(self.pre_types[:self.n_child])
        for c, io in zip(self.calls, self.input_ordinals):
            out.append(_call_dtype(c, self.pre_types, io))
        return out


def _call_dtype(c: WindowCall, pre_types, inp_ord: int) -> dt.DType:
    from spark_rapids_tpu.expressions.aggregates import (AggregateFunction,
                                                         Average, Count)

    if c.fn in ("row_number", "rank", "dense_rank"):
        return dt.INT32
    if isinstance(c.fn, tuple):
        return pre_types[inp_ord]
    assert isinstance(c.fn, AggregateFunction)
    if isinstance(c.fn, Count):
        return dt.INT64
    if isinstance(c.fn, Average):
        return dt.FLOAT64
    return c.fn.dtype
