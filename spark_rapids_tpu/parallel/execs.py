"""Planner-reachable mesh execution: aggregate + shuffled join execs.

Round 1 left the mesh path as standalone step kernels; these execs make it
a *planner capability* (VERDICT round-1 item #2): when a Session runs with
``rapids.tpu.mesh.enabled``, the planner lowers

  partial-agg -> hash ShuffleExchange -> final-agg
      onto ``MeshGroupByExec`` (one shard_map program: all_to_all hash
      route + per-chip sort-based aggregation — parallel/shuffle.py), and
  hash-Exchange(L) + hash-Exchange(R) -> ShuffledHashJoinExec
      onto ``MeshShuffledJoinExec`` (parallel/join_step.py: both sides
      routed in-program, per-chip sorted-hash probe), and
  global SortNode onto ``MeshSortExec`` (sampled range bounds +
      all_to_all + per-chip sort — parallel/sort_step.py).

This mirrors how GpuShuffleExchangeExec transparently swaps Spark's
exchange for the UCX transport (GpuShuffleExchangeExec.scala:146-248,
RapidsShuffleInternalManager.scala:90-191) — except the TPU-native
transport is XLA collectives over ICI, so "exchange + downstream exec"
fuse into one compiled program instead of a writer/reader pair.

Sharded hand-off (round-3 verdict item #6): a mesh exec whose child chain
is itself on the mesh — directly, or through reference-only projections —
consumes the child's ``DistributedBatch`` without gathering to the host:
join→join chains, join→groupby inputs and sort-over-mesh stay device-
resident between collectives, and only the TOP mesh exec gathers at
collect time. The host staging hop remains exactly at the leaves (scan
output; the io layer places shards on a real multi-host pod) and at
groupby OUTPUTS (the final aggregate evaluation — avg = sum/count etc. —
runs as a single-device projection after the gather).
"""
from __future__ import annotations

import dataclasses
import time
import types
from typing import Dict, Iterator, List, Optional, Tuple, Union

import jax
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.execs.aggregate import HashAggregateExec
from spark_rapids_tpu.execs.window import WindowExec
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Expression)
from spark_rapids_tpu.expressions.compiler import CompiledFilter
from spark_rapids_tpu.ops.buckets import bucket_capacity
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.ops.filter import rebucket
from spark_rapids_tpu.parallel.join_step import (
    DistributedExpandJoinStep, DistributedShuffledJoinStep)
from spark_rapids_tpu.parallel.mesh import DATA_AXIS
from spark_rapids_tpu.parallel.shuffle import (DistributedGroupByStep,
                                               distributed_batch_from_host)
from spark_rapids_tpu.utils.tracing import TraceRange

_KIND_MAP = {"inner": "inner", "left": "left", "left_semi": "leftsemi",
             "left_anti": "leftanti", "full": "full"}


@dataclasses.dataclass
class DistributedBatch:
    """A relation living sharded over the mesh: per-column global device
    arrays (row-sharded ``P(axis)``, ``n_dev * cap`` long), per-device
    live counts, and host-side template columns carrying string
    dictionaries. This is the hand-off unit between chained mesh execs —
    no host copy, no gather."""

    datas: List
    valids: List
    counts: object  # (n_dev,) int32, sharded over the mesh axis
    cap: int
    dtypes: List[dt.DType]
    templates: List[Optional[Column]]

    def select(self, ordinals: List[int]) -> "DistributedBatch":
        return DistributedBatch(
            [self.datas[i] for i in ordinals],
            [self.valids[i] for i in ordinals],
            self.counts, self.cap,
            [self.dtypes[i] for i in ordinals],
            [self.templates[i] for i in ordinals])

    def total_rows(self) -> int:
        return int(np.asarray(jax.device_get(self.counts)).sum())


def _shard_batch(mesh, batch: ColumnarBatch, dtypes: List[dt.DType]):
    """Row-shard a single-device batch over the mesh (host staging hop).
    String columns shard their int32 codes; dictionaries stay host-side
    with the template column."""
    n = batch.realized_num_rows()
    # ONE device_get over the whole batch (device_get takes a pytree;
    # None validities pass through as empty nodes): the per-column loop
    # this replaces paid one ~105 ms RTT per data/validity array
    host = jax.device_get([(c.data, c.validity) for c in batch.columns])
    arrays = [np.asarray(d)[:n] for d, _v in host]
    valids = [None if v is None else np.asarray(v)[:n] for _d, v in host]
    return distributed_batch_from_host(mesh, arrays, dtypes,
                                       validities=valids)


def _to_sharded(mesh, batch: ColumnarBatch,
                dtypes: List[dt.DType]) -> DistributedBatch:
    datas, valids, counts, cap = _shard_batch(mesh, batch, dtypes)
    return DistributedBatch(datas, valids, counts, cap, list(dtypes),
                            list(batch.columns))


def _gather_sharded(out_datas, out_valids, counts, dtypes: List[dt.DType],
                    templates: List[Optional[Column]], n_dev: int
                    ) -> ColumnarBatch:
    """Collect per-shard live prefixes into one batch, rebuilding string
    columns onto their template dictionaries."""
    # ONE device_get for every shard's data, validity, and counts
    # (was 2 x n_cols + 1 transfers — each a full RTT behind the tunnel)
    hd, hv, hn = jax.device_get((list(out_datas), list(out_valids),
                                 counts))
    host_d = [np.asarray(d) for d in hd]
    host_v = [np.asarray(v) for v in hv]
    ns = np.atleast_1d(np.asarray(hn))
    rcap = len(host_d[0]) // n_dev
    total = int(ns.sum())
    cap = bucket_capacity(max(total, 1))
    cols: List[Column] = []
    for i, t in enumerate(dtypes):
        parts_d = [host_d[i][dev * rcap:dev * rcap + int(ns[dev])]
                   for dev in range(n_dev)]
        parts_v = [host_v[i][dev * rcap:dev * rcap + int(ns[dev])]
                   for dev in range(n_dev)]
        vals = np.concatenate(parts_d) if total else \
            np.zeros(0, dtype=t.np_dtype)
        mask = np.concatenate(parts_v) if total else np.zeros(0, bool)
        tpl = templates[i]
        if t is dt.STRING and isinstance(tpl, StringColumn):
            import jax.numpy as jnp

            codes = np.zeros(cap, dtype=np.int32)
            codes[:total] = vals
            full_mask = np.zeros(cap, dtype=bool)
            full_mask[:total] = mask
            cols.append(StringColumn(jnp.asarray(codes), tpl.dictionary,
                                     jnp.asarray(full_mask)))
        else:
            cols.append(Column.from_numpy(vals, t, validity=mask,
                                          capacity=cap))
    return ColumnarBatch(cols, total)


def _gather_db(db: DistributedBatch, n_dev: int) -> ColumnarBatch:
    return _gather_sharded(db.datas, db.valids, db.counts, db.dtypes,
                           db.templates, n_dev)


def _ref_only_ordinals(exprs: List[Expression]) -> Optional[List[int]]:
    """Ordinal list when every projection expr is a bare (possibly
    aliased) column reference — a projection that is pure column
    selection and can be applied to a DistributedBatch for free."""
    ords: List[int] = []
    for e in exprs:
        while isinstance(e, Alias):
            e = e.children[0]
        if not isinstance(e, BoundReference):
            return None
        ords.append(e.ordinal)
    return ords


def _mesh_source(child: TpuExec):
    """(mesh_exec, ops) when ``child`` is a mesh exec wrapped only in
    chain-preserving operators; None otherwise. ``ops`` is the TOP-DOWN
    list of operations to replay bottom-up on the mesh result:
    ("select", ordinals) for reference-only projections, ("filter",
    filter_exec) for deterministic device-only filters (applied per
    chip — parallel/filter_step.py — so the chain never gathers).
    Single-batch coalesces are transparent over a mesh child (there is
    nothing to re-batch)."""
    from spark_rapids_tpu.execs.basic import FilterExec, ProjectExec
    from spark_rapids_tpu.execs.batching import CoalesceBatchesExec

    ops: List[Tuple[str, object]] = []
    node = child
    while True:
        if isinstance(node, ProjectExec):
            inner = _ref_only_ordinals(node.projection.exprs)
            if inner is None:
                return None
            ops.append(("select", inner))
            node = node.children[0]
        elif isinstance(node, FilterExec) and node.filter.fused and \
                node.filter.condition.deterministic:
            ops.append(("filter", node))
            node = node.children[0]
        elif isinstance(node, CoalesceBatchesExec):
            node = node.children[0]
        else:
            break
    if isinstance(node, (MeshGroupByExec, MeshShuffledJoinExec,
                         MeshSortExec, MeshWindowExec)):
        return node, ops
    return None


_FILTER_STEPS: Dict[Tuple, object] = {}


def _apply_mesh_filter(fexec, r: "DistributedBatch",
                       mesh) -> "DistributedBatch":
    from spark_rapids_tpu.parallel.filter_step import DistributedFilterStep

    cond = fexec.filter.condition
    ckey = cond.tree_key()
    if ckey is None:
        # un-keyable condition: never share (an id()-based key can be
        # reused by a new exec after GC and run the WRONG condition)
        step = getattr(fexec, "_mesh_filter_step", None)
        if step is None or step.mesh is not mesh or \
                step.dtypes != tuple(r.dtypes):
            step = DistributedFilterStep(mesh, r.dtypes, cond)
            fexec._mesh_filter_step = step
    else:
        # mesh identity is part of the key: session_mesh rebuilds the
        # mesh when the device count changes, and a step compiled for
        # the old mesh must not see the new sharding
        key = (id(mesh), ckey, tuple(r.dtypes))
        step = _FILTER_STEPS.get(key)
        if step is None:
            if len(_FILTER_STEPS) >= 256:  # bound like _FUSED_CACHE
                _FILTER_STEPS.clear()
            step = DistributedFilterStep(mesh, r.dtypes, cond)
            _FILTER_STEPS[key] = step
    od, ov, counts = step(r.datas, r.valids, r.counts)
    return DistributedBatch(list(od), list(ov), counts, r.cap,
                            list(r.dtypes), list(r.templates))


def _eval_source(child: TpuExec
                 ) -> Optional[Union[DistributedBatch, ColumnarBatch]]:
    """Execute a mesh child chain, staying sharded when the mesh path
    succeeded (the result may still be a host batch when the child fell
    back, e.g. the join dup-flag path). None when the child is not a
    mesh chain — the caller drains it normally."""
    ms = _mesh_source(child)
    if ms is None:
        return None
    node, ops = ms
    # record into the mesh child's own metrics: this path bypasses the
    # timed() iterator of execute(), and without it the child's runtime
    # would be misattributed to the consuming exec's self time
    child0 = sum(c.metrics.pipeline_time_ns for c in node.children)
    t0 = time.perf_counter_ns()
    r = node.execute_any()
    elapsed = time.perf_counter_ns() - t0
    child_ns = sum(c.metrics.pipeline_time_ns
                   for c in node.children) - child0
    if isinstance(r, DistributedBatch):
        rows = types.SimpleNamespace(num_rows=r.counts.sum())
        node.metrics.record(rows, elapsed, child_ns)
    else:
        node.metrics.record(r, elapsed, child_ns)
    for kind, arg in reversed(ops):
        if kind == "select":
            # identity requires FULL width: a strict-prefix projection
            # must still select, or the consumer sees extra columns
            width = len(r.dtypes) if isinstance(r, DistributedBatch) \
                else len(r.columns)
            if arg != list(range(width)):
                r = r.select(arg)
        elif isinstance(r, DistributedBatch):
            r = _apply_mesh_filter(arg, r, node.mesh)
        else:
            r = arg.filter(r)
    return r


def _drain_exec(child: TpuExec) -> ColumnarBatch:
    batches = []
    for p in range(child.num_partitions):
        batches.extend(b for b in child.execute(p)
                       if b.realized_num_rows() > 0)
    if not batches:
        return ColumnarBatch.empty(child.schema)
    return batches[0] if len(batches) == 1 else concat_batches(batches)


class _MeshShippable:
    """Cluster map-task pickling for mesh execs: the live Mesh (Device
    handles) and compiled step caches stay behind; only the axis SIZE
    ships, and the receiving executor reconstructs an equivalent mesh
    over its own devices (parallel/mesh.py reconstruct_mesh) — the
    round-4 verdict's mesh-inside-cluster composition. Workers must
    boot with enough (virtual) devices; the cluster runtime passes the
    session mesh size to every spawned worker."""

    def __getstate__(self):
        from spark_rapids_tpu.parallel.mesh import mesh_model_size

        state = dict(self.__dict__)
        mesh = state.pop("mesh", None)
        state.pop("_steps", None)
        state.pop("_dstep", None)
        state["_mesh_n"] = None if mesh is None else \
            int(mesh.shape[DATA_AXIS])
        state["_mesh_model"] = 1 if mesh is None else \
            int(mesh_model_size(mesh))
        return state

    def __setstate__(self, state):
        from spark_rapids_tpu.parallel.mesh import reconstruct_mesh

        n = state.pop("_mesh_n", None)
        model = state.pop("_mesh_model", 1)
        self.__dict__.update(state)
        self._steps = {}
        self._dstep = None
        self.mesh = None if n is None else reconstruct_mesh(n, model)


class MeshGroupByExec(_MeshShippable, HashAggregateExec):
    """Complete-mode aggregation lowered onto the mesh: the partial/
    exchange/final pipeline collapses into one all_to_all + local-groupby
    program per chip (hash routing gives each chip a disjoint key space,
    so no merge stage is needed — see parallel/shuffle.py).

    Input side consumes a sharded child chain directly when the input
    projection is pure column selection; the OUTPUT always gathers — the
    final aggregate evaluation (avg = sum/count, variance terms) runs as
    a single-device projection."""

    def __init__(self, grouping: List[Expression], aggs, child: TpuExec,
                 schema: Schema, conf, mesh):
        self.mesh = mesh
        self._steps: Dict[Tuple, DistributedGroupByStep] = {}
        super().__init__(grouping, aggs, child, schema, mode="complete",
                         conf=conf)

    @property
    def num_partitions(self) -> int:
        return 1

    def _step(self) -> DistributedGroupByStep:
        key = (tuple(self.input_types), len(self.grouping),
               tuple(self.first_specs))
        if key not in self._steps:
            self._steps[key] = DistributedGroupByStep(
                self.mesh, tuple(self.input_types),
                tuple(range(len(self.grouping))),
                tuple(self.first_specs))
        return self._steps[key]

    def execute_any(self) -> ColumnarBatch:
        db_in: Optional[DistributedBatch] = None
        ords = _ref_only_ordinals(self.input_proj.exprs) \
            if self.input_proj is not None else None
        src = _eval_source(self.children[0]) if ords is not None \
            else None
        if src is not None:
            # the mesh child already executed — never re-execute it
            if isinstance(src, ColumnarBatch):
                if src.realized_num_rows() == 0:
                    return ColumnarBatch.empty(self.schema)
                db_in = _to_sharded(self.mesh, src.select(ords),
                                    self.input_types)
            else:
                db_in = src.select(ords)
        if db_in is None:
            child = self.children[0]
            projected = []
            for p in range(child.num_partitions):
                for b in child.execute(p):
                    if b.realized_num_rows() == 0:
                        continue
                    projected.append(self.input_proj(b))
            if not projected:
                return ColumnarBatch.empty(self.schema)
            merged = concat_batches(projected) if len(projected) > 1 \
                else projected[0]
            db_in = _to_sharded(self.mesh, merged, self.input_types)
        n_dev = self.mesh.shape[DATA_AXIS]
        with TraceRange("MeshGroupByExec.step"):
            step = self._step()
            od, ov, ng = step(db_in.datas, db_in.valids, db_in.counts)
        templates: List[Optional[Column]] = \
            [db_in.templates[i] for i in range(len(self.grouping))]
        # agg outputs: strings keep the input column's dictionary
        # (min/max/first/last on codes == on strings, sorted dicts)
        for spec in self.first_specs:
            templates.append(db_in.templates[spec.ordinal]
                             if spec.ordinal >= 0 else None)
        out = _gather_sharded(od, ov, ng, step.output_dtypes(),
                              templates, n_dev)
        return rebucket(self.final_proj(out))

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            yield self.execute_any()
        return timed(self, it())


class MeshShuffledJoinExec(_MeshShippable, TpuExec):
    """Equi-join lowered onto the mesh. Build side is chosen at execute
    time by realized row counts (the AQE-style smallest-side heuristic);
    the unique-build contract is checked in-program and violations fall
    back to the single-device sort-probe kernel — correctness never
    depends on the contract holding.

    Sides consume sharded child chains directly (join→join pipelines);
    string join keys require host dictionary unification, so they gather
    first. ``execute_any`` hands the sharded result to a chained parent
    when the mesh path succeeded and no residual condition is pending."""

    def __init__(self, kind: str, left: TpuExec, right: TpuExec,
                 left_keys: List[int], right_keys: List[int],
                 schema: Schema, condition: Optional[Expression],
                 conf, mesh):
        super().__init__([left, right], schema)
        assert kind in _KIND_MAP, kind
        self.kind = kind
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.conf = conf
        self.mesh = mesh
        self.condition = CompiledFilter(condition, conf) \
            if condition is not None else None
        self._steps: Dict[Tuple, object] = {}

    @property
    def num_partitions(self) -> int:
        return 1

    def _get_step(self, kind, sdt, bdt, skeys, bkeys):
        key = (kind, tuple(sdt), tuple(bdt), tuple(skeys), tuple(bkeys))
        if key not in self._steps:
            self._steps[key] = DistributedShuffledJoinStep(
                self.mesh, kind, sdt, bdt, skeys, bkeys)
        return self._steps[key]

    def _get_expand_step(self, kind, sdt, bdt, skey, bkey, ocap):
        key = ("expand", kind, tuple(sdt), tuple(bdt), skey, bkey, ocap)
        if key not in self._steps:
            self._steps[key] = DistributedExpandJoinStep(
                self.mesh, kind, sdt, bdt, skey, bkey, ocap)
        return self._steps[key]

    def _run_mesh_expand(self, kind, stream: DistributedBatch,
                         build: DistributedBatch, skey: int, bkey: int
                         ) -> Optional[DistributedBatch]:
        """Exact many-to-many single-key join on the mesh; grows the
        static output bucket on overflow (pow2 buckets bound the
        recompiles). None after repeated overflow — caller falls back."""
        n_dev = self.mesh.shape[DATA_AXIS]
        sdt, bdt = tuple(stream.dtypes), tuple(build.dtypes)
        ocap = bucket_capacity(n_dev * (stream.cap + build.cap))
        # the step returns the TRUE per-chip join sizes, so one resize
        # always suffices: attempt 1 sizes, attempt 2 runs exact
        for _attempt in range(2):
            step = self._get_expand_step(kind, sdt, bdt, skey, bkey,
                                         ocap)
            od, ov, counts, totals = step(
                stream.datas, stream.valids, stream.counts,
                build.datas, build.valids, build.counts)
            need = int(np.asarray(jax.device_get(totals)).max())
            if need <= ocap:
                templates = list(stream.templates)
                if step.emits_build_columns:
                    templates += list(build.templates)
                out_cap = od[0].shape[0] // n_dev
                return DistributedBatch(list(od), list(ov), counts,
                                        out_cap,
                                        list(step.output_dtypes()),
                                        templates)
            ocap = bucket_capacity(need)
        return None

    def _run_mesh(self, kind, stream: DistributedBatch,
                  build: DistributedBatch, skeys, bkeys
                  ) -> Optional[DistributedBatch]:
        """One mesh attempt; None when the dup flag fired."""
        n_dev = self.mesh.shape[DATA_AXIS]
        step = self._get_step(kind, tuple(stream.dtypes),
                              tuple(build.dtypes), tuple(skeys),
                              tuple(bkeys))
        od, ov, counts, dups = step(
            stream.datas, stream.valids, stream.counts,
            build.datas, build.valids, build.counts)
        if bool(np.asarray(jax.device_get(dups)).any()):
            return None
        templates = list(stream.templates)
        if step.emits_build_columns:
            templates += list(build.templates)
        out_cap = od[0].shape[0] // n_dev
        return DistributedBatch(list(od), list(ov), counts, out_cap,
                                list(step.output_dtypes()), templates)

    def _source(self, idx: int
                ) -> Union[DistributedBatch, ColumnarBatch]:
        src = _eval_source(self.children[idx])
        if src is None:
            src = _drain_exec(self.children[idx])
        return src

    def _unified_host_pair(self, left_s, right_s, left_keys, right_keys
                           ) -> Tuple[ColumnarBatch, ColumnarBatch]:
        """Gather both sides to the host (when sharded) and unify string
        join-key dictionaries — the single staging sequence every
        string-keyed path shares."""
        from spark_rapids_tpu.ops.join import unify_join_strings

        n_dev = self.mesh.shape[DATA_AXIS]
        left_b = left_s if isinstance(left_s, ColumnarBatch) \
            else _gather_db(left_s, n_dev)
        right_b = right_s if isinstance(right_s, ColumnarBatch) \
            else _gather_db(right_s, n_dev)
        return unify_join_strings(left_b, right_b, left_keys, right_keys)

    def _compute(self) -> Union[DistributedBatch, ColumnarBatch]:
        ltypes = list(self.children[0].schema.types)
        rtypes = list(self.children[1].schema.types)
        left_s = self._source(0)
        right_s = self._source(1)
        if self.kind == "full":
            # FULL OUTER as a composition over the same mesh machinery:
            # left join (all L rows + matches) UNION the null-extended
            # anti of R against L (exactly the unmatched R rows). The
            # reference emits both sides' unmatched rows from one kernel
            # (GpuHashJoin.scala FullOuter); here each half is its own
            # all_to_all program and a sharded union step composes them
            unified = False
            if any(ltypes[k] is dt.STRING for k in self.left_keys):
                # unify string-key dictionaries ONCE for both halves —
                # each _compute_kind would otherwise gather + unify +
                # re-shard both sides independently
                left_b, right_b = self._unified_host_pair(
                    left_s, right_s, self.left_keys, self.right_keys)
                left_s = _to_sharded(self.mesh, left_b, ltypes)
                right_s = _to_sharded(self.mesh, right_b, rtypes)
                unified = True
            left_part = self._compute_kind(
                "left", left_s, right_s, self.left_keys,
                self.right_keys, ltypes, rtypes, keys_unified=unified)
            anti_part = self._compute_kind(
                "leftanti", right_s, left_s, self.right_keys,
                self.left_keys, rtypes, ltypes, keys_unified=unified)
            return self._full_union(left_part, anti_part, ltypes, rtypes)
        return self._compute_kind(_KIND_MAP[self.kind], left_s, right_s,
                                  self.left_keys, self.right_keys,
                                  ltypes, rtypes)

    def _full_union(self, left_part, anti_part, ltypes: List[dt.DType],
                    rtypes: List[dt.DType]
                    ) -> Union[DistributedBatch, ColumnarBatch]:
        n_dev = self.mesh.shape[DATA_AXIS]
        if isinstance(left_part, DistributedBatch) and \
                isinstance(anti_part, DistributedBatch):
            # both halves live sharded → union stays sharded (round-3
            # verdict: _gather_db here broke the sharded hand-off)
            from spark_rapids_tpu.parallel.join_step import \
                DistributedNullExtendUnionStep

            key = ("full_union", tuple(ltypes), tuple(rtypes))
            if key not in self._steps:
                self._steps[key] = DistributedNullExtendUnionStep(
                    self.mesh, ltypes, rtypes)
            step = self._steps[key]
            od, ov, counts = step(left_part.datas, left_part.valids,
                                  left_part.counts, anti_part.datas,
                                  anti_part.valids, anti_part.counts)
            out_cap = od[0].shape[0] // n_dev
            # anti-half right columns carry the same dictionaries as the
            # left half's build side (both views of the same right input)
            return DistributedBatch(list(od), list(ov), counts, out_cap,
                                    list(ltypes) + list(rtypes),
                                    list(left_part.templates))
        lp = left_part if isinstance(left_part, ColumnarBatch) \
            else _gather_db(left_part, n_dev)
        ap = anti_part if isinstance(anti_part, ColumnarBatch) \
            else _gather_db(anti_part, n_dev)
        n_un = ap.realized_num_rows()
        if n_un == 0:
            return lp
        null_left = [Column.all_null(t, ap.capacity) for t in ltypes]
        extended = ColumnarBatch(null_left + list(ap.columns), n_un)
        return concat_batches([lp, extended])

    def _compute_kind(self, kind, left_s, right_s, left_keys,
                      right_keys, ltypes, rtypes, keys_unified=False
                      ) -> Union[DistributedBatch, ColumnarBatch]:
        from spark_rapids_tpu.ops.join import equi_join

        # string join keys need one dictionary across both sides — a
        # host operation, so string-keyed joins stage through the host
        # (unless the caller already unified them: the FULL OUTER branch
        # does it once for both halves).
        # NOTE: only the left_keys/right_keys PARAMETERS are used below —
        # the FULL OUTER anti half calls this with the sides (and key
        # ordinal lists) swapped, so self.left_keys would apply left-side
        # ordinals to the right-side relation (r3 advisor finding)
        str_keys = not keys_unified and \
            any(ltypes[k] is dt.STRING for k in left_keys)
        left_b = right_b = None
        if str_keys:
            left_b, right_b = self._unified_host_pair(
                left_s, right_s, left_keys, right_keys)
            left_db = _to_sharded(self.mesh, left_b, ltypes)
            right_db = _to_sharded(self.mesh, right_b, rtypes)
        else:
            left_db = left_s if isinstance(left_s, DistributedBatch) \
                else _to_sharded(self.mesh, left_s, ltypes)
            right_db = right_s if isinstance(right_s, DistributedBatch) \
                else _to_sharded(self.mesh, right_s, rtypes)
        out: Optional[DistributedBatch] = None
        if len(left_keys) == 1:
            # single-key: the EXACT expansion step handles arbitrary
            # many-to-many fan-out on the mesh — no dup bailout
            # (round-2 verdict: fact x fact joins silently degraded
            # to one device)
            with TraceRange(f"MeshShuffledJoinExec.expand.{kind}"):
                out = self._run_mesh_expand(
                    kind, left_db, right_db, left_keys[0],
                    right_keys[0])
            if out is not None:
                return out
        flippable = (kind == "inner" and
                     left_db.total_rows() < right_db.total_rows())
        with TraceRange(f"MeshShuffledJoinExec.{kind}"):
            if flippable:
                # smaller LEFT side becomes the build; output columns
                # come back build-first, reordered below
                out = self._run_mesh(kind, right_db, left_db,
                                     right_keys, left_keys)
                if out is not None:
                    nl, nr = len(ltypes), len(rtypes)
                    out = out.select(
                        list(range(nr, nr + nl)) + list(range(nr)))
            if out is None:
                out = self._run_mesh(kind, left_db, right_db,
                                     left_keys, right_keys)
            if out is None and kind == "inner" and not flippable:
                out = self._run_mesh(kind, right_db, left_db,
                                     right_keys, left_keys)
                if out is not None:
                    nl, nr = len(ltypes), len(rtypes)
                    out = out.select(
                        list(range(nr, nr + nl)) + list(range(nr)))
            if out is None:
                # many-to-many (both orientations dup-flagged): the
                # single-device kernel handles arbitrary fan-out
                if left_b is None:
                    left_b, right_b = self._unified_host_pair(
                        left_s, right_s, left_keys, right_keys)
                host_out, _ = equi_join(left_b, right_b, left_keys,
                                        right_keys, ltypes, rtypes,
                                        join_type=kind)
                return host_out
        return out

    def execute_any(self) -> Union[DistributedBatch, ColumnarBatch]:
        r = self._compute()
        if isinstance(r, DistributedBatch):
            if self.condition is None:
                return r
            r = _gather_db(r, self.mesh.shape[DATA_AXIS])
        if self.condition is not None:
            r = self.condition(r)
        return r

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            r = self.execute_any()
            if isinstance(r, DistributedBatch):
                r = _gather_db(r, self.mesh.shape[DATA_AXIS])
            yield r
        return timed(self, it())


class MeshWindowExec(_MeshShippable, WindowExec):
    """Window functions lowered onto the mesh: the planner's hash
    exchange on PARTITION BY keys + per-partition window
    (GpuWindowExec.scala:92) fuse into one all_to_all + per-chip
    sort + segmented-scan program (parallel/window_step.py). Hash
    routing puts each partition-by group wholly on one chip, so results
    are exact with no merge. Consumes sharded child chains when the
    pre-projection is pure column selection; emits a DistributedBatch
    for chained mesh parents (rank-filter-join pipelines stay
    device-resident)."""

    def __init__(self, partition_ordinals, order_specs, calls,
                 child: TpuExec, schema: Schema, conf, mesh):
        super().__init__(partition_ordinals, order_specs, calls, child,
                         schema, conf)
        assert partition_ordinals, \
            "un-partitioned windows stay single-device"
        self.mesh = mesh
        self._dstep = None

    @property
    def num_partitions(self) -> int:
        return 1

    @property
    def children_coalesce_goal(self):
        # the single-device exec demands one batch; the mesh exec drains
        # and stages its own input — a coalesce here would sever the
        # sharded hand-off from a mesh child (the inserted
        # CoalesceBatchesExec hides the child from _mesh_source)
        return [None]

    def _step(self):
        from spark_rapids_tpu.parallel.window_step import \
            DistributedWindowStep

        if self._dstep is None:
            self._dstep = DistributedWindowStep(
                self.mesh, tuple(self.pre_types),
                tuple(self.partition_ordinals), tuple(self.order_specs),
                tuple(self.calls), tuple(self._input_ordinal),
                self.n_child)
        return self._dstep

    def execute_any(self) -> Union[DistributedBatch, ColumnarBatch]:
        ords = _ref_only_ordinals(self.pre_proj.exprs)
        src = _eval_source(self.children[0])
        db_in: Optional[DistributedBatch] = None
        if src is not None and isinstance(src, DistributedBatch) and \
                ords is not None:
            if src.total_rows() == 0:
                return ColumnarBatch.empty(self.schema)
            db_in = src.select(ords)
        else:
            b = _drain_exec(self.children[0]) if src is None else src
            if isinstance(b, DistributedBatch):
                # sharded child but a computing pre-projection: the
                # projection is host-orchestrated, so stage through it
                b = _gather_db(b, self.mesh.shape[DATA_AXIS])
            if b.realized_num_rows() == 0:
                return ColumnarBatch.empty(self.schema)
            db_in = _to_sharded(self.mesh, self.pre_proj(b),
                                self.pre_types)
        n_dev = self.mesh.shape[DATA_AXIS]
        with TraceRange("MeshWindowExec.step"):
            step = self._step()
            od, ov, ns = step(db_in.datas, db_in.valids, db_in.counts)
        templates: List[Optional[Column]] = \
            list(db_in.templates[:self.n_child])
        for c, io in zip(self.calls, self._input_ordinal):
            # lead/lag/first/last over strings reuse the input column's
            # dictionary; numeric calls carry no template
            templates.append(db_in.templates[io]
                             if io >= 0 and
                             self.pre_types[io] is dt.STRING else None)
        out_cap = od[0].shape[0] // n_dev
        return DistributedBatch(list(od), list(ov), ns, out_cap,
                                step.output_dtypes(), templates)

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            r = self.execute_any()
            if isinstance(r, DistributedBatch):
                r = _gather_db(r, self.mesh.shape[DATA_AXIS])
            yield r
        return timed(self, it())


class MeshSortExec(_MeshShippable, TpuExec):
    """Global ORDER BY lowered onto the mesh: sampled range bounds +
    all_to_all routing + per-chip lexicographic sort in ONE program
    (parallel/sort_step.py) — the multi-chip answer to the reference's
    GpuRangePartitioner + GpuSortExec pipeline. Device order == global
    order, so gathering shard prefixes in device order IS the sorted
    relation. Consumes sharded child chains directly (sort-over-join
    stays on the mesh; string sort keys ride dictionary codes, whose
    order IS lexicographic order for sorted dictionaries)."""

    def __init__(self, specs, child: TpuExec, schema: Schema, conf,
                 mesh):
        super().__init__([child], schema)
        self.specs = list(specs)
        self.conf = conf
        self.mesh = mesh
        self._steps: Dict[Tuple, object] = {}

    @property
    def num_partitions(self) -> int:
        return 1

    def _step(self, dtypes):
        from spark_rapids_tpu.parallel.sort_step import \
            DistributedSortStep

        key = tuple(dtypes)
        if key not in self._steps:
            self._steps[key] = DistributedSortStep(
                self.mesh, dtypes, self.specs)
        return self._steps[key]

    def execute_any(self) -> Union[DistributedBatch, ColumnarBatch]:
        dtypes = list(self.schema.types)
        n_dev = self.mesh.shape[DATA_AXIS]
        src = _eval_source(self.children[0])
        if src is None:
            merged = _drain_exec(self.children[0])
            if merged.realized_num_rows() == 0:
                return ColumnarBatch.empty(self.schema)
            db = _to_sharded(self.mesh, merged, dtypes)
        elif isinstance(src, ColumnarBatch):
            if src.realized_num_rows() == 0:
                return ColumnarBatch.empty(self.schema)
            db = _to_sharded(self.mesh, src, dtypes)
        else:
            db = src
        with TraceRange("MeshSortExec.step"):
            od, ov, ns = self._step(tuple(dtypes))(db.datas, db.valids,
                                                   db.counts)
        out_cap = od[0].shape[0] // n_dev
        # shard prefixes in DEVICE ORDER are the global order —
        # _gather_sharded concatenates exactly that way
        return DistributedBatch(list(od), list(ov), ns, out_cap, dtypes,
                                list(db.templates))

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            r = self.execute_any()
            if isinstance(r, DistributedBatch):
                r = _gather_db(r, self.mesh.shape[DATA_AXIS])
            yield r
        return timed(self, it())
