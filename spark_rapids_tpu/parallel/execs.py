"""Planner-reachable mesh execution: aggregate + shuffled join execs.

Round 1 left the mesh path as standalone step kernels; these execs make it
a *planner capability* (VERDICT round-1 item #2): when a Session runs with
``rapids.tpu.mesh.enabled``, the planner lowers

  partial-agg -> hash ShuffleExchange -> final-agg
      onto ``MeshGroupByExec`` (one shard_map program: all_to_all hash
      route + per-chip sort-based aggregation — parallel/shuffle.py), and
  hash-Exchange(L) + hash-Exchange(R) -> ShuffledHashJoinExec
      onto ``MeshShuffledJoinExec`` (parallel/join_step.py: both sides
      routed in-program, per-chip sorted-hash probe).

This mirrors how GpuShuffleExchangeExec transparently swaps Spark's
exchange for the UCX transport (GpuShuffleExchangeExec.scala:146-248,
RapidsShuffleInternalManager.scala:90-191) — except the TPU-native
transport is XLA collectives over ICI, so "exchange + downstream exec"
fuse into one compiled program instead of a writer/reader pair.

Single-host staging note: children stream single-device batches; the exec
re-shards rows over the mesh through a host staging hop. On a real
multi-host pod the scan itself would place shards (io layer growth, not a
kernel change) — the collective path exercised here is exactly the
on-mesh program that runs there.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.execs.aggregate import HashAggregateExec
from spark_rapids_tpu.expressions.base import Expression
from spark_rapids_tpu.expressions.compiler import CompiledFilter
from spark_rapids_tpu.ops.buckets import bucket_capacity
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.ops.filter import rebucket
from spark_rapids_tpu.parallel.join_step import (
    DistributedExpandJoinStep, DistributedShuffledJoinStep)
from spark_rapids_tpu.parallel.mesh import DATA_AXIS
from spark_rapids_tpu.parallel.shuffle import (DistributedGroupByStep,
                                               distributed_batch_from_host)
from spark_rapids_tpu.utils.tracing import TraceRange

_KIND_MAP = {"inner": "inner", "left": "left", "left_semi": "leftsemi",
             "left_anti": "leftanti"}


def _shard_batch(mesh, batch: ColumnarBatch, dtypes: List[dt.DType]):
    """Row-shard a single-device batch over the mesh (host staging hop).
    String columns shard their int32 codes; dictionaries stay host-side
    with the template column."""
    n = batch.realized_num_rows()
    arrays, valids = [], []
    for c in batch.columns:
        arrays.append(np.asarray(jax.device_get(c.data))[:n])
        valids.append(None if c.validity is None else
                      np.asarray(jax.device_get(c.validity))[:n])
    return distributed_batch_from_host(mesh, arrays, dtypes,
                                       validities=valids)


def _gather_sharded(out_datas, out_valids, counts, dtypes: List[dt.DType],
                    templates: List[Optional[Column]], n_dev: int
                    ) -> ColumnarBatch:
    """Collect per-shard live prefixes into one batch, rebuilding string
    columns onto their template dictionaries."""
    host_d = [np.asarray(jax.device_get(d)) for d in out_datas]
    host_v = [np.asarray(jax.device_get(v)) for v in out_valids]
    ns = np.atleast_1d(np.asarray(jax.device_get(counts)))
    rcap = len(host_d[0]) // n_dev
    total = int(ns.sum())
    cap = bucket_capacity(max(total, 1))
    cols: List[Column] = []
    for i, t in enumerate(dtypes):
        parts_d = [host_d[i][dev * rcap:dev * rcap + int(ns[dev])]
                   for dev in range(n_dev)]
        parts_v = [host_v[i][dev * rcap:dev * rcap + int(ns[dev])]
                   for dev in range(n_dev)]
        vals = np.concatenate(parts_d) if total else \
            np.zeros(0, dtype=t.np_dtype)
        mask = np.concatenate(parts_v) if total else np.zeros(0, bool)
        tpl = templates[i]
        if t is dt.STRING and isinstance(tpl, StringColumn):
            import jax.numpy as jnp

            codes = np.zeros(cap, dtype=np.int32)
            codes[:total] = vals
            full_mask = np.zeros(cap, dtype=bool)
            full_mask[:total] = mask
            cols.append(StringColumn(jnp.asarray(codes), tpl.dictionary,
                                     jnp.asarray(full_mask)))
        else:
            cols.append(Column.from_numpy(vals, t, validity=mask,
                                          capacity=cap))
    return ColumnarBatch(cols, total)


class MeshGroupByExec(HashAggregateExec):
    """Complete-mode aggregation lowered onto the mesh: the partial/
    exchange/final pipeline collapses into one all_to_all + local-groupby
    program per chip (hash routing gives each chip a disjoint key space,
    so no merge stage is needed — see parallel/shuffle.py)."""

    def __init__(self, grouping: List[Expression], aggs, child: TpuExec,
                 schema: Schema, conf, mesh):
        self.mesh = mesh
        self._steps: Dict[Tuple, DistributedGroupByStep] = {}
        super().__init__(grouping, aggs, child, schema, mode="complete",
                         conf=conf)

    @property
    def num_partitions(self) -> int:
        return 1

    def _step(self) -> DistributedGroupByStep:
        key = (tuple(self.input_types), len(self.grouping),
               tuple(self.first_specs))
        if key not in self._steps:
            self._steps[key] = DistributedGroupByStep(
                self.mesh, tuple(self.input_types),
                tuple(range(len(self.grouping))),
                tuple(self.first_specs))
        return self._steps[key]

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            child = self.children[0]
            projected = []
            for p in range(child.num_partitions):
                for b in child.execute(p):
                    if b.realized_num_rows() == 0:
                        continue
                    projected.append(self.input_proj(b))
            if not projected:
                yield ColumnarBatch.empty(self.schema)
                return
            merged = concat_batches(projected) if len(projected) > 1 \
                else projected[0]
            n_dev = self.mesh.shape[DATA_AXIS]
            with TraceRange("MeshGroupByExec.step"):
                datas, valids, counts, _ = _shard_batch(
                    self.mesh, merged, self.input_types)
                step = self._step()
                od, ov, ng = step(datas, valids, counts)
            templates: List[Optional[Column]] = \
                [merged.columns[i] for i in range(len(self.grouping))]
            # agg outputs: strings keep the input column's dictionary
            # (min/max/first/last on codes == on strings, sorted dicts)
            for spec in self.first_specs:
                templates.append(merged.columns[spec.ordinal]
                                 if spec.ordinal >= 0 else None)
            out = _gather_sharded(od, ov, ng, step.output_dtypes(),
                                  templates, n_dev)
            yield rebucket(self.final_proj(out))
        return timed(self, it())


class MeshShuffledJoinExec(TpuExec):
    """Equi-join lowered onto the mesh. Build side is chosen at execute
    time by realized row counts (the AQE-style smallest-side heuristic);
    the unique-build contract is checked in-program and violations fall
    back to the single-device sort-probe kernel — correctness never
    depends on the contract holding."""

    def __init__(self, kind: str, left: TpuExec, right: TpuExec,
                 left_keys: List[int], right_keys: List[int],
                 schema: Schema, condition: Optional[Expression],
                 conf, mesh):
        super().__init__([left, right], schema)
        assert kind in _KIND_MAP, kind
        self.kind = kind
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.conf = conf
        self.mesh = mesh
        self.condition = CompiledFilter(condition, conf) \
            if condition is not None else None
        self._steps: Dict[Tuple, DistributedShuffledJoinStep] = {}

    @property
    def num_partitions(self) -> int:
        return 1

    def _drain(self, child: TpuExec) -> ColumnarBatch:
        batches = []
        for p in range(child.num_partitions):
            batches.extend(b for b in child.execute(p)
                           if b.realized_num_rows() > 0)
        if not batches:
            return ColumnarBatch.empty(child.schema)
        return batches[0] if len(batches) == 1 else concat_batches(batches)

    def _get_step(self, kind, sdt, bdt, skeys, bkeys):
        key = (kind, tuple(sdt), tuple(bdt), tuple(skeys), tuple(bkeys))
        if key not in self._steps:
            self._steps[key] = DistributedShuffledJoinStep(
                self.mesh, kind, sdt, bdt, skeys, bkeys)
        return self._steps[key]

    def _get_expand_step(self, kind, sdt, bdt, skey, bkey, ocap):
        key = ("expand", kind, tuple(sdt), tuple(bdt), skey, bkey, ocap)
        if key not in self._steps:
            self._steps[key] = DistributedExpandJoinStep(
                self.mesh, kind, sdt, bdt, skey, bkey, ocap)
        return self._steps[key]

    def _run_mesh_expand(self, kind, stream: ColumnarBatch,
                         build: ColumnarBatch, skey: int, bkey: int,
                         sdt, bdt) -> Optional[ColumnarBatch]:
        """Exact many-to-many single-key join on the mesh; grows the
        static output bucket on overflow (pow2 buckets bound the
        recompiles). None after repeated overflow — caller falls back."""
        n_dev = self.mesh.shape[DATA_AXIS]
        s_sh = _shard_batch(self.mesh, stream, sdt)
        b_sh = _shard_batch(self.mesh, build, bdt)
        ocap = bucket_capacity(n_dev * (s_sh[3] + b_sh[3]))
        # the step returns the TRUE per-chip join sizes, so one resize
        # always suffices: attempt 1 sizes, attempt 2 runs exact
        for _attempt in range(2):
            step = self._get_expand_step(kind, tuple(sdt), tuple(bdt),
                                         skey, bkey, ocap)
            od, ov, counts, totals = step(s_sh[0], s_sh[1], s_sh[2],
                                          b_sh[0], b_sh[1], b_sh[2])
            need = int(np.asarray(jax.device_get(totals)).max())
            if need <= ocap:
                templates = list(stream.columns)
                if step.emits_build_columns:
                    templates += list(build.columns)
                return _gather_sharded(od, ov, counts,
                                       step.output_dtypes(),
                                       templates, n_dev)
            ocap = bucket_capacity(need)
        return None

    def _run_mesh(self, kind, stream: ColumnarBatch, build: ColumnarBatch,
                  skeys, bkeys, sdt, bdt) -> Optional[ColumnarBatch]:
        """One mesh attempt; None when the dup flag fired."""
        n_dev = self.mesh.shape[DATA_AXIS]
        s_sh = _shard_batch(self.mesh, stream, sdt)
        b_sh = _shard_batch(self.mesh, build, bdt)
        step = self._get_step(kind, sdt, bdt, skeys, bkeys)
        od, ov, counts, dups = step(s_sh[0], s_sh[1], s_sh[2],
                                    b_sh[0], b_sh[1], b_sh[2])
        if bool(np.asarray(jax.device_get(dups)).any()):
            return None
        templates = list(stream.columns)
        if step.emits_build_columns:
            templates += list(build.columns)
        return _gather_sharded(od, ov, counts, step.output_dtypes(),
                               templates, n_dev)

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.ops.join import equi_join, unify_join_strings

        def it():
            left_b = self._drain(self.children[0])
            right_b = self._drain(self.children[1])
            left_b, right_b = unify_join_strings(
                left_b, right_b, self.left_keys, self.right_keys)
            ltypes = list(self.children[0].schema.types)
            rtypes = list(self.children[1].schema.types)
            kind = _KIND_MAP[self.kind]
            out: Optional[ColumnarBatch] = None
            if len(self.left_keys) == 1:
                # single-key: the EXACT expansion step handles arbitrary
                # many-to-many fan-out on the mesh — no dup bailout
                # (round-2 verdict: fact x fact joins silently degraded
                # to one device)
                with TraceRange(f"MeshShuffledJoinExec.expand.{kind}"):
                    out = self._run_mesh_expand(
                        kind, left_b, right_b, self.left_keys[0],
                        self.right_keys[0], ltypes, rtypes)
                if out is not None:
                    if self.condition is not None:
                        out = self.condition(out)
                    yield out
                    return
            flippable = (kind == "inner" and
                         left_b.realized_num_rows() <
                         right_b.realized_num_rows())
            with TraceRange(f"MeshShuffledJoinExec.{kind}"):
                if flippable:
                    # smaller LEFT side becomes the build; output columns
                    # come back build-first, reordered below
                    out = self._run_mesh(kind, right_b, left_b,
                                         self.right_keys, self.left_keys,
                                         rtypes, ltypes)
                    if out is not None:
                        nl, nr = len(ltypes), len(rtypes)
                        out = out.select(
                            list(range(nr, nr + nl)) + list(range(nr)))
                if out is None:
                    out = self._run_mesh(kind, left_b, right_b,
                                         self.left_keys, self.right_keys,
                                         ltypes, rtypes)
                if out is None and kind == "inner" and not flippable:
                    out = self._run_mesh(kind, right_b, left_b,
                                         self.right_keys, self.left_keys,
                                         rtypes, ltypes)
                    if out is not None:
                        nl, nr = len(ltypes), len(rtypes)
                        out = out.select(
                            list(range(nr, nr + nl)) + list(range(nr)))
                if out is None:
                    # many-to-many (both orientations dup-flagged): the
                    # single-device kernel handles arbitrary fan-out
                    out, _ = equi_join(left_b, right_b, self.left_keys,
                                       self.right_keys, ltypes, rtypes,
                                       join_type=kind)
            if self.condition is not None:
                out = self.condition(out)
            yield out
        return timed(self, it())


class MeshSortExec(TpuExec):
    """Global ORDER BY lowered onto the mesh: sampled range bounds +
    all_to_all routing + per-chip lexicographic sort in ONE program
    (parallel/sort_step.py) — the multi-chip answer to the reference's
    GpuRangePartitioner + GpuSortExec pipeline. Device order == global
    order, so gathering shard prefixes in device order IS the sorted
    relation."""

    def __init__(self, specs, child: TpuExec, schema: Schema, conf,
                 mesh):
        super().__init__([child], schema)
        self.specs = list(specs)
        self.conf = conf
        self.mesh = mesh
        self._steps: Dict[Tuple, object] = {}

    @property
    def num_partitions(self) -> int:
        return 1

    def _step(self, dtypes):
        from spark_rapids_tpu.parallel.sort_step import \
            DistributedSortStep

        key = tuple(dtypes)
        if key not in self._steps:
            self._steps[key] = DistributedSortStep(
                self.mesh, dtypes, self.specs)
        return self._steps[key]

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            child = self.children[0]
            batches = []
            for p in range(child.num_partitions):
                batches.extend(b for b in child.execute(p)
                               if b.realized_num_rows() > 0)
            if not batches:
                yield ColumnarBatch.empty(self.schema)
                return
            merged = concat_batches(batches) if len(batches) > 1 \
                else batches[0]
            dtypes = list(self.schema.types)
            n_dev = self.mesh.shape[DATA_AXIS]
            with TraceRange("MeshSortExec.step"):
                datas, valids, counts, _ = _shard_batch(
                    self.mesh, merged, dtypes)
                od, ov, ns = self._step(tuple(dtypes))(datas, valids,
                                                       counts)
            templates = list(merged.columns)
            # shard prefixes in DEVICE ORDER are the global order —
            # _gather_sharded concatenates exactly that way
            yield _gather_sharded(od, ov, ns, dtypes, templates, n_dev)
        return timed(self, it())
