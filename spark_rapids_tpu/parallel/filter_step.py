"""Sharded filter: mask + per-chip compaction over a DistributedBatch.

A FilterExec between two mesh execs would otherwise sever the sharded
hand-off (the chain gathers to host, filters, re-shards — exactly the
round trip the hand-off design removes). Filters are embarrassingly
parallel: the condition evaluates per chip with the SAME expression
evaluator the single-device compiled filter uses (expressions/compiler
EvalContext), then one variadic sort per chip compacts kept rows to the
live prefix (the scatter-free compaction idiom of parallel/shuffle.py).
No collectives at all — rows never change chips.

Only deterministic device-only conditions lower here; nondeterministic
ones (rand) keep the single-device path where TaskInfo row bases are
well-defined.
"""
from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.parallel.mesh import DATA_AXIS
from spark_rapids_tpu.shims import get_shims


class DistributedFilterStep:
    """Compiled per-chip mask + compact for one (condition, dtypes)."""

    def __init__(self, mesh: Mesh, dtypes: Sequence[dt.DType], condition,
                 axis: str = DATA_AXIS):
        self.mesh = mesh
        self.dtypes = tuple(dtypes)
        self.condition = condition
        self.axis = axis
        self.n_dev = mesh.shape[axis]
        self._fn = self._build()

    def _build(self):
        dtypes = self.dtypes
        condition = self.condition

        def device_step(datas, valids, n_rows):
            from spark_rapids_tpu.expressions.compiler import (ColV,
                                                               EvalContext,
                                                               broadcast)
            from spark_rapids_tpu.expressions.nondeterministic import \
                TaskInfo

            cap = datas[0].shape[0]
            cols = [ColV(t, d, v)
                    for t, d, v in zip(dtypes, datas, valids)]
            ctx = EvalContext(cols, cap, n_rows[0], in_jit=True,
                              task_info=TaskInfo.make())
            v = broadcast(condition.eval(ctx), ctx)
            keep = v.data if v.validity is None else (v.data & v.validity)
            iota = jnp.arange(cap, dtype=jnp.int32)
            keep = keep & (iota < n_rows[0])
            payload = tuple(datas) + tuple(valids)
            packed = jax.lax.sort(
                ((~keep).astype(jnp.int32),) + payload, num_keys=1,
                is_stable=True)[1:]
            new_n = jnp.sum(keep).astype(jnp.int32)
            out_d = list(packed[:len(datas)])
            out_v = [vv & (iota < new_n) for vv in packed[len(datas):]]
            return out_d, out_v, new_n.reshape(1)

        n_cols = len(self.dtypes)
        in_specs = ([P(self.axis)] * n_cols, [P(self.axis)] * n_cols,
                    P(self.axis))
        out_specs = ([P(self.axis)] * n_cols, [P(self.axis)] * n_cols,
                     P(self.axis))
        fn = get_shims().shard_map()(device_step, mesh=self.mesh,
                                     in_specs=in_specs,
                                     out_specs=out_specs)
        return jax.jit(fn)

    def __call__(self, datas: List[jax.Array], valids: List[jax.Array],
                 counts: jax.Array):
        return self._fn(datas, valids, counts)
