"""Device mesh construction.

The reference pins one GPU per executor process and scales by adding
executors (GpuDeviceManager.scala:72-118). The TPU analogue is a single
process owning an N-chip mesh: data parallelism is an axis of a
``jax.sharding.Mesh``, and the shuffle's "executors" are mesh positions.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

DATA_AXIS = "data"


def data_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over ``n_devices`` chips with a single data axis — the
    shuffle/partition axis (the reference's executor set)."""
    import numpy as np

    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def mesh_axis_size(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    return mesh.shape[axis]


_SESSION_MESH: Optional[Mesh] = None


def session_mesh(conf) -> Optional[Mesh]:
    """The planner-visible mesh: None unless ``rapids.tpu.mesh.enabled``.
    Cached process-wide (meshes are cheap but identity-stable mesh objects
    keep shard_map caches warm). A device count larger than the attached
    backend clamps to what exists — the driver's virtual-CPU dry run sets
    the backend size before planning."""
    from spark_rapids_tpu import config as cfg

    if conf is None or not conf.get(cfg.MESH_ENABLED):
        return None
    global _SESSION_MESH
    want = conf.get(cfg.MESH_DEVICES) or 0
    avail = len(jax.devices())
    n = min(want, avail) if want > 0 else avail
    if n < 2:
        return None  # a 1-chip mesh adds collectives for nothing
    if _SESSION_MESH is None or _SESSION_MESH.shape[DATA_AXIS] != n:
        _SESSION_MESH = data_mesh(n)
    return _SESSION_MESH


_RECONSTRUCTED: dict = {}


def reconstruct_mesh(n: int) -> Mesh:
    """Worker-side mesh reconstruction from a shipped spec (axis size):
    cluster map tasks carry mesh subtrees as specs, never live Device
    handles — the receiving process builds an equivalent mesh over its
    OWN devices (the reference ships GPU ids and re-opens handles
    per-executor the same way, GpuDeviceManager.scala:72-118). Cached
    per size: identity-stable meshes keep shard_map caches warm."""
    got = _RECONSTRUCTED.get(n)
    if got is not None:
        return got
    devs = jax.devices()
    assert len(devs) >= n, (
        f"shipped mesh subtree needs {n} devices; this process has "
        f"{len(devs)} — spawn executors with "
        f"xla_force_host_platform_device_count >= {n}")
    m = data_mesh(n)
    _RECONSTRUCTED[n] = m
    return m


def force_cpu_mesh(n_devices: int) -> None:
    """Ensure at least ``n_devices`` devices exist, falling back to a
    virtual CPU mesh when the attached backend has fewer (e.g. one real
    TPU chip). Used by multi-chip dry runs and mesh benchmarks."""
    import os

    # set the flag BEFORE the first backend touch: XLA parses XLA_FLAGS
    # once at client creation, and late-0.4.x jax cannot grow the CPU
    # device count after that (clear_backends no longer re-reads it).
    # Harmless on real accelerators — it only sizes the host platform.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    if len(jax.devices()) >= n_devices:
        return
    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.shims import get_shims

    get_shims().clear_backends()
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:
        # pre-0.5 jax has no jax_num_cpu_devices knob; the XLA_FLAGS
        # device-count flag set above does the job on backend rebuild
        pass
    assert len(jax.devices()) >= n_devices, (
        f"need {n_devices} devices, have {jax.devices()} — this jax "
        f"cannot resize an initialized backend; set "
        f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices} "
        f"before process start")
