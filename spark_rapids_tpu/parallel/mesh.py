"""Device mesh construction.

The reference pins one GPU per executor process and scales by adding
executors (GpuDeviceManager.scala:72-118). The TPU analogue is a single
process owning an N-chip mesh: data parallelism is an axis of a
``jax.sharding.Mesh``, and the shuffle's "executors" are mesh positions.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

DATA_AXIS = "data"


def data_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over ``n_devices`` chips with a single data axis — the
    shuffle/partition axis (the reference's executor set)."""
    import numpy as np

    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def mesh_axis_size(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    return mesh.shape[axis]
