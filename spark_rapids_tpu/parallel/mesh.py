"""Device mesh construction and multi-host topology.

The reference pins one GPU per executor process and scales by adding
executors (GpuDeviceManager.scala:72-118). The TPU analogue is a pod of
hosts: each host (process) owns an N-chip mesh slice with explicit
``data`` x ``model`` axes and runs ONE SPMD program over it — data
parallelism is the shuffle/partition axis, the model axis is reserved
for tensor-parallel operators. Between hosts sits the DCN seam, carried
by the TCP exchange path (shuffle/tcp.py); inside a host, collectives
ride ICI in-program. :class:`HostTopology` is the explicit map of that
layout, and every clamp or downgrade the mesh builder applies is
recorded (``mesh_fallback_snapshot``) so the runner can surface it next
to the shuffle-fallback telemetry instead of silently shrinking.
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
from jax.sharding import Mesh

from spark_rapids_tpu.utils import lockorder

DATA_AXIS = "data"
MODEL_AXIS = "model"

# {reason: count} — process-wide, snapshot/delta like the spmd fallback
# telemetry so a runner reports only its own run's mesh downgrades.
_mesh_fallbacks: dict = {}
_fb_lock = lockorder.make_lock("parallel.mesh.fallbacks")


def record_mesh_fallback(reason: str) -> None:
    """Count one mesh construction that did not deliver what the conf
    asked for (device clamp, model axis dropped, ...)."""
    with _fb_lock:
        _mesh_fallbacks[reason] = _mesh_fallbacks.get(reason, 0) + 1


def mesh_fallback_snapshot() -> dict:
    with _fb_lock:
        return dict(sorted(_mesh_fallbacks.items()))


def mesh_fallback_delta(before: dict) -> dict:
    """Mesh fallbacks recorded since ``before`` (a snapshot)."""
    now = mesh_fallback_snapshot()
    return {k: n - before.get(k, 0) for k, n in now.items()
            if n - before.get(k, 0)}


def data_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over ``n_devices`` chips with a single data axis — the
    shuffle/partition axis (the reference's executor set)."""
    import numpy as np

    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def data_model_mesh(n_data: int, n_model: int = 1,
                    devices: Optional[Sequence] = None) -> Mesh:
    """2-D ``(data, model)`` mesh over ``n_data * n_model`` chips. With
    ``n_model == 1`` this returns the plain 1-D data mesh so every
    existing shard_map spec (and its compile cache) is untouched."""
    import numpy as np

    if n_model <= 1:
        return data_mesh(n_data, devices)
    if devices is None:
        devices = jax.devices()
    need = n_data * n_model
    assert len(devices) >= need, (
        f"data x model mesh needs {n_data}x{n_model}={need} devices, "
        f"have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def mesh_axis_size(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    return mesh.shape[axis]


def mesh_model_size(mesh: Mesh) -> int:
    """Model-axis width of ``mesh`` (1 for 1-D data meshes)."""
    return mesh.shape[MODEL_AXIS] if MODEL_AXIS in mesh.axis_names else 1


class HostTopology(NamedTuple):
    """Explicit multi-host axis layout: ``n_hosts`` processes, each
    owning a ``data x model`` mesh slice of ``devices_per_host`` chips.
    The global data axis is the concatenation of the per-host data
    slices; collectives inside a slice are in-program ICI, anything
    crossing a host boundary is the DCN seam (TCP exchange path)."""

    n_hosts: int
    devices_per_host: int
    model: int = 1

    @property
    def data_per_host(self) -> int:
        """Data-axis width of one host's slice."""
        return max(self.devices_per_host // max(self.model, 1), 1)

    @property
    def global_data(self) -> int:
        """Total data-axis width across the pod."""
        return self.n_hosts * self.data_per_host

    @property
    def total_devices(self) -> int:
        return self.n_hosts * self.devices_per_host

    def host_of(self, global_data_index: int) -> int:
        """Which host owns position ``global_data_index`` of the global
        data axis (hosts hold contiguous slices)."""
        assert 0 <= global_data_index < self.global_data, \
            f"data index {global_data_index} outside {self.global_data}"
        return global_data_index // self.data_per_host

    def seam(self, src_data_index: int, dst_data_index: int) -> str:
        """The link class a transfer between two global data positions
        crosses: ``"ici"`` inside one host's slice, ``"dcn"`` between
        hosts."""
        return ("ici" if self.host_of(src_data_index)
                == self.host_of(dst_data_index) else "dcn")

    def axis_layout(self) -> dict:
        """JSON-friendly layout summary for telemetry/docs."""
        return {"hosts": self.n_hosts,
                "data_per_host": self.data_per_host,
                "model": self.model,
                "global_data": self.global_data,
                "total_devices": self.total_devices}


def session_topology(conf) -> Optional[HostTopology]:
    """The session's host topology, or None when the mesh is off.
    Host count from ``rapids.tpu.mesh.hosts``; 0 infers it from cluster
    membership (driver + workers) when cluster mode is on, else 1. The
    per-host slice is the session mesh of THIS process — every host
    runs the same SPMD program shape over its own devices."""
    from spark_rapids_tpu import config as cfg

    if conf is None or not conf.get(cfg.MESH_ENABLED):
        return None
    hosts = conf.get(cfg.MESH_HOSTS) or 0
    if hosts <= 0:
        hosts = 1
        if conf.get(cfg.CLUSTER_ENABLED):
            hosts += max(conf.get(cfg.CLUSTER_WORKERS) or 0, 0)
    m = session_mesh(conf)
    if m is not None:
        per_host = len(m.devices.flat)
        model = mesh_model_size(m)
    else:
        per_host = len(jax.devices())
        model = 1
    return HostTopology(n_hosts=hosts, devices_per_host=per_host,
                        model=model)


_SESSION_MESH: Optional[Mesh] = None


def session_mesh(conf) -> Optional[Mesh]:
    """The planner-visible mesh: None unless ``rapids.tpu.mesh.enabled``.
    Cached process-wide (meshes are cheap but identity-stable mesh objects
    keep shard_map caches warm). A device count larger than the attached
    backend clamps to what exists — the driver's virtual-CPU dry run sets
    the backend size before planning — and the clamp is RECORDED as a
    mesh fallback, never silent. ``rapids.tpu.mesh.modelDevices`` > 1
    carves a model axis out of the device budget (data = devices //
    model); a model axis that leaves fewer than 2 data devices is
    dropped, with the reason recorded."""
    from spark_rapids_tpu import config as cfg

    if conf is None or not conf.get(cfg.MESH_ENABLED):
        return None
    global _SESSION_MESH
    want = conf.get(cfg.MESH_DEVICES) or 0
    avail = len(jax.devices())
    n = min(want, avail) if want > 0 else avail
    if 0 < avail < want:
        record_mesh_fallback(
            f"{cfg.MESH_DEVICES.key}={want} exceeds the attached "
            f"backend ({avail} devices): clamped to {avail}")
    if n < 2:
        return None  # a 1-chip mesh adds collectives for nothing
    model = max(conf.get(cfg.MESH_MODEL_DEVICES) or 1, 1)
    if model > 1 and n // model < 2:
        record_mesh_fallback(
            f"{cfg.MESH_MODEL_DEVICES.key}={model} leaves fewer than 2 "
            f"data devices out of {n}: model axis dropped")
        model = 1
    n_data = n // model if model > 1 else n
    if _SESSION_MESH is None \
            or _SESSION_MESH.shape[DATA_AXIS] != n_data \
            or mesh_model_size(_SESSION_MESH) != model:
        _SESSION_MESH = data_model_mesh(n_data, model)
    return _SESSION_MESH


_RECONSTRUCTED: dict = {}


def reconstruct_mesh(n: int, model: int = 1) -> Mesh:
    """Worker-side mesh reconstruction from a shipped spec (axis sizes):
    cluster map tasks carry mesh subtrees as specs, never live Device
    handles — the receiving process builds an equivalent mesh over its
    OWN devices (the reference ships GPU ids and re-opens handles
    per-executor the same way, GpuDeviceManager.scala:72-118). Cached
    per (data, model) size: identity-stable meshes keep shard_map
    caches warm."""
    model = max(int(model or 1), 1)
    got = _RECONSTRUCTED.get((n, model))
    if got is not None:
        return got
    devs = jax.devices()
    need = n * model
    assert len(devs) >= need, (
        f"shipped mesh subtree needs {need} devices; this process has "
        f"{len(devs)} — spawn executors with "
        f"xla_force_host_platform_device_count >= {need}")
    m = data_model_mesh(n, model)
    _RECONSTRUCTED[(n, model)] = m
    return m


def force_cpu_mesh(n_devices: int) -> None:
    """Ensure at least ``n_devices`` devices exist, falling back to a
    virtual CPU mesh when the attached backend has fewer (e.g. one real
    TPU chip). Used by multi-chip dry runs and mesh benchmarks."""
    import os

    # set the flag BEFORE the first backend touch: XLA parses XLA_FLAGS
    # once at client creation, and late-0.4.x jax cannot grow the CPU
    # device count after that (clear_backends no longer re-reads it).
    # Harmless on real accelerators — it only sizes the host platform.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    if len(jax.devices()) >= n_devices:
        return
    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.shims import get_shims

    get_shims().clear_backends()
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:
        # pre-0.5 jax has no jax_num_cpu_devices knob; the XLA_FLAGS
        # device-count flag set above does the job on backend rebuild
        pass
    assert len(jax.devices()) >= n_devices, (
        f"need {n_devices} devices, have {jax.devices()} — this jax "
        f"cannot resize an initialized backend; set "
        f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices} "
        f"before process start")
