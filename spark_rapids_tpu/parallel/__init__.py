"""Multi-chip parallelism: device meshes and ICI collective shuffles.

TPU-native replacement for the reference's distributed backend (SURVEY.md
§2.8, §5.8): where the reference moves shuffle partitions between executor
GPUs over UCX/RDMA with a tag protocol (shuffle-plugin/.../ucx/UCX.scala),
the TPU design keeps data resident across a ``jax.sharding.Mesh`` and
exchanges rows with ``jax.lax.all_to_all`` under ``shard_map`` — the
collective rides ICI within a slice and DCN across slices, scheduled by XLA
rather than a hand-written progress thread.
"""
from spark_rapids_tpu.parallel.mesh import (  # noqa: F401
    data_mesh,
    mesh_axis_size,
)
from spark_rapids_tpu.parallel.shuffle import (  # noqa: F401
    DistributedGroupByStep,
    distributed_batch_from_host,
    gather_distributed_result,
)
