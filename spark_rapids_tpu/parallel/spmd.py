"""SPMD whole-stage decision: does a shuffle boundary fold into the
compiled program, and if not, why.

The reference keeps shuffle data on-device through a UCX/RDMA transport
(PAPER L7); our same-slice analogue is an in-program
``jax.lax.all_to_all`` over the session mesh — the exchange becomes a
collective inside the enclosing stage's shard_map program, so a
distributed stage costs one launch instead of a host round trip per
block. TCP (shuffle/tcp.py) stays as the cross-host DCN fallback and as
the path for plans whose stages cannot be uniformly sharded.

This module is the ONE place that decision lives. Planner rules call
:func:`in_program_mesh` instead of reading the mesh directly; every
"no" answer on a mesh-enabled session is recorded with a reason, and
the run telemetry (benchmarks/runner.py ``shuffle_fallbacks``) surfaces
the counts — a plan silently staying on the host path is a bug class
this PR retires.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

from spark_rapids_tpu.utils import lockorder

# {(op, reason): count} — process-wide, snapshot/delta like dispatch
# telemetry so a runner reports only its own run's fallbacks.
_fallbacks: dict = {}
_lock = lockorder.make_lock("parallel.spmd.fallbacks")


def record_fallback(op: str, reason: str) -> None:
    """Count one mesh-requested shuffle that stayed on the host/TCP
    path. ``op`` names the planner shape (join/groupby/sort/window/
    exchange), ``reason`` the gate that said no."""
    with _lock:
        key = (op, reason)
        _fallbacks[key] = _fallbacks.get(key, 0) + 1


def fallback_snapshot() -> dict:
    """{"op: reason": count} so far (flattened for JSON telemetry)."""
    with _lock:
        return {f"{op}: {reason}": n
                for (op, reason), n in sorted(_fallbacks.items())}


def fallback_delta(before: dict) -> dict:
    """Fallbacks recorded since ``before`` (a fallback_snapshot)."""
    now = fallback_snapshot()
    return {k: n - before.get(k, 0) for k, n in now.items()
            if n - before.get(k, 0)}


#: seam classes a shuffle decision lands on (parallel/mesh.HostTopology
#: uses the same vocabulary): ICI = in-program collective inside one
#: host's mesh slice, DCN = host boundary crossed over the TCP exchange
#: path.
SEAM_ICI = "ici"
SEAM_DCN = "dcn"

# {(op, seam, reason): count} — every ICI-vs-DCN decision, not just the
# "no" answers: the multi-host fence (scripts/multihost_chaos_check.py)
# asserts both sides of the seam were exercised.
_seams: dict = {}


def record_seam(op: str, seam: str, reason: str) -> None:
    """Count one seam decision for ``op``'s shuffle: which link class
    (SEAM_ICI / SEAM_DCN) carries it and why."""
    with _lock:
        key = (op, seam, reason)
        _seams[key] = _seams.get(key, 0) + 1


def seam_snapshot() -> dict:
    """{"op: seam: reason": count} so far (flattened for JSON)."""
    with _lock:
        return {f"{op}: {seam}: {reason}": n
                for (op, seam, reason), n in sorted(_seams.items())}


def seam_delta(before: dict) -> dict:
    """Seam decisions recorded since ``before`` (a seam_snapshot)."""
    now = seam_snapshot()
    return {k: n - before.get(k, 0) for k, n in now.items()
            if n - before.get(k, 0)}


#: the RUNTIME fallback reason (every other reason is a plan-time gate
#: in in_program_mesh below): an in-program exchange's compiled program
#: failed on-device mid-query and the stage re-ran on the host/TCP
#: path — recorded once per degraded exchange, surfaced in the same
#: telemetry (docs/fault-tolerance.md)
DEGRADE_DEVICE_ERROR = ("device error: in-program exchange degraded "
                        "to host/TCP path")


def is_degradable_device_error(err: BaseException) -> bool:
    """Whether an in-program exchange failure is a DEVICE error worth
    degrading to the host/TCP path (OOM, XLA runtime fault), as opposed
    to a plan/user error that would fail identically on the host."""
    from spark_rapids_tpu.memory.retry import is_oom_error

    if is_oom_error(err):
        return True
    return type(err).__name__ in ("XlaRuntimeError", "JaxRuntimeError",
                                  "InternalError")


def record_degrade(op: str) -> None:
    """Count one in-program exchange degraded at RUNTIME by a device
    error (execs/exchange._materialize_in_program_once)."""
    record_fallback(op, DEGRADE_DEVICE_ERROR)


class SkewSpec(NamedTuple):
    """AQE skew-detection parameters resolved once at plan time and
    carried to the two places that act on them: the host-path paired
    readers (sub-read splitting) and the in-program exchange (salting
    before the all_to_all). One spec type keeps both paths detecting
    the SAME partitions as skewed."""

    factor: float
    threshold: int
    max_splits: int


def adaptive_skew_spec(conf) -> Optional[SkewSpec]:
    """The session's skew spec, or None when AQE skew handling is off
    (either gate: adaptive.enabled or adaptive.skewJoin.enabled)."""
    from spark_rapids_tpu import config as cfg

    if conf is None or not conf.get(cfg.ADAPTIVE_ENABLED) \
            or not conf.get(cfg.ADAPTIVE_SKEW_JOIN):
        return None
    return SkewSpec(conf.get(cfg.ADAPTIVE_SKEW_FACTOR),
                    conf.get(cfg.ADAPTIVE_SKEW_THRESHOLD),
                    max(conf.get(cfg.ADAPTIVE_SKEW_MAX_SPLITS), 2))


def in_program_mesh(conf, op: str, *, keyed: bool = True,
                    reason_if_unkeyed: str = "",
                    est_rows: Optional[int] = None,
                    cluster_local: bool = False):
    """The mesh to lower ``op``'s shuffle onto when the in-program path
    applies, else None with the fallback reason recorded.

    Gates, in order (first "no" wins and is the recorded reason):

    - mesh not requested (``rapids.tpu.mesh.enabled`` off / no conf):
      None, NOT recorded — there is no shuffle decision to explain.
    - ``rapids.tpu.shuffle.inProgram.enabled`` off: explicit opt-out.
    - ``rapids.tpu.cluster.enabled``: this shuffle's blocks cross the
      host boundary, so the DCN seam (TCP, shuffle/tcp.py) carries it.
      This is a PER-SEAM decision, not an all-or-nothing cluster gate:
      when ``cluster_local`` — a Mesh*Exec subtree ships to one
      executor whole, so its internal collective only ever spans that
      process's local mesh slice (fenced by tests/test_cluster_sql.py's
      mesh-subtree-on-worker case) — the shuffle stays ICI in-program
      even in cluster mode, unless
      ``rapids.tpu.shuffle.seam.intraHostIci.enabled`` restores the
      old blanket gate. Both outcomes are recorded as seam decisions
      (:func:`record_seam`) on top of the fallback reason.
    - a model-parallel axis on the session mesh: the in-program
      exchange's collectives ride the data axis only.
    - fewer than 2 visible devices: no axis to collect over.
    - ``keyed`` False: the plan shape cannot be uniformly sharded
      (callers pass the concrete reason, e.g. an ungrouped aggregate).
    - ``est_rows`` below ``rapids.tpu.shuffle.inProgram.minRows``.
    """
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.parallel.mesh import (mesh_model_size,
                                                session_mesh)

    if conf is None or not conf.get(cfg.MESH_ENABLED):
        return None
    if not conf.get(cfg.SHUFFLE_IN_PROGRAM):
        record_fallback(op, "disabled by "
                        + cfg.SHUFFLE_IN_PROGRAM.key)
        return None
    cluster = bool(conf.get(cfg.CLUSTER_ENABLED))
    if cluster and not cluster_local:
        record_seam(op, SEAM_DCN, "inter-host exchange: blocks cross "
                    "the process boundary, TCP carries the DCN seam")
        record_fallback(op, "cross-host DCN: cluster mode shuffles "
                        "over TCP (shuffle/tcp.py)")
        return None
    if cluster and not conf.get(cfg.SHUFFLE_SEAM_ICI):
        record_seam(op, SEAM_DCN, "intra-host ICI disabled by "
                    + cfg.SHUFFLE_SEAM_ICI.key)
        record_fallback(op, "disabled by " + cfg.SHUFFLE_SEAM_ICI.key)
        return None
    mesh = session_mesh(conf)
    if mesh is None:
        record_fallback(op, "mesh unavailable: fewer than 2 devices")
        return None
    if mesh_model_size(mesh) > 1:
        record_fallback(op, "model-parallel axis active: in-program "
                        "shuffle rides the data axis only")
        return None
    if not keyed:
        record_fallback(op, "non-uniform: "
                        + (reason_if_unkeyed or "no shard keys"))
        return None
    floor = conf.get(cfg.SHUFFLE_IN_PROGRAM_MIN_ROWS)
    if floor and est_rows is not None and est_rows < floor:
        record_fallback(
            op, f"below {cfg.SHUFFLE_IN_PROGRAM_MIN_ROWS.key} "
                f"({est_rows} < {floor})")
        return None
    if cluster:
        record_seam(op, SEAM_ICI, "intra-host slice: collective spans "
                    "one process's devices")
    else:
        record_seam(op, SEAM_ICI, "single host: no DCN seam in session")
    return mesh
