"""Benchmark: q5-like scan→filter→groupby-aggregate throughput, TPU vs CPU.

The driver runs this on real TPU hardware at the end of every round and
records the JSON line. Models BASELINE.md config #1 (the reference's
integration-test q5-like: parquet-scan + filter + hash aggregate,
integration_tests/.../TpchLikeSpark.scala methodology): identical relational
work is timed on the TPU pipeline and on a pandas CPU baseline, and the
ratio is reported (the reference's own headline metric is this CPU-vs-GPU
speedup shape, docs/FAQ.md:60-67).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import time

import numpy as np


def seed_compile_cache() -> None:
    """Seed .jax_cache with the tracked TPU executable for the bench
    pipeline (scripts/bench_cache/). A cold XLA compile of the 4M-row
    fused kernel takes ~30 min over the remote-compile tunnel; the
    persistent cache makes a fresh process start hot, and this seeding
    survives even a clean checkout. Stale entries (from kernel edits)
    are harmless — the cache key simply won't match.

    NOTE (builder discipline): after ANY change to ops/groupby.py or the
    entry pipeline, re-run `python bench.py` once without a timeout and
    refresh scripts/bench_cache/ with the new jit_step-* entry.
    `python scripts/check_bench_cache.py` verifies the seed still
    matches (trace + cache probe, no compile) — run it before every
    commit that touches the kernel."""
    root = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(root, "scripts", "bench_cache")
    dst = os.path.join(root, ".jax_cache")
    if not os.path.isdir(src):
        return
    os.makedirs(dst, exist_ok=True)
    for name in os.listdir(src):
        target = os.path.join(dst, name)
        if not os.path.exists(target):
            shutil.copy2(os.path.join(src, name), target)


def refresh_cache_seed() -> None:
    """After a TPU bench run, sync the tracked seed with the live
    cache: new jit_step entries (a kernel edit happened) are copied in
    and superseded ones pruned, so the driver's end-of-round commit
    carries the fresh seed automatically — a stale seed costs ONE cold
    compile on this box instead of a manual refresh ritual (round-4
    verdict #9)."""
    import jax

    if jax.devices()[0].platform == "cpu":
        return  # cache keys are platform-specific; only seed TPU entries
    root = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(root, ".jax_cache")
    dst = os.path.join(root, "scripts", "bench_cache")
    if not os.path.isdir(src) or not os.path.isdir(dst):
        return
    live = {f for f in os.listdir(src) if f.startswith("jit_step-")}
    if not live:
        return
    tracked = set(os.listdir(dst))
    for f in sorted(live - tracked):
        shutil.copy2(os.path.join(src, f), os.path.join(dst, f))
        print(f"bench: refreshed cache seed {f}", file=sys.stderr)
    # bound the tracked seed: keep the newest few entries (the live
    # plain + telemetry-wrapped variants); older kernels' multi-MB
    # binaries age out instead of accumulating. (A set-difference prune
    # can't work here — seed_compile_cache copies every tracked entry
    # into .jax_cache at startup, so tracked is always a subset of
    # live.)
    seeds = sorted(
        (f for f in os.listdir(dst) if f.startswith("jit_step-")),
        key=lambda f: os.path.getmtime(os.path.join(dst, f)),
        reverse=True)
    for f in seeds[3:]:
        os.remove(os.path.join(dst, f))


N_ROWS = 4_000_000
N_KEYS = 65_536
WARMUP = 2
ITERS = 5


def gen_data(n=N_ROWS, seed=7):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, N_KEYS, n).astype(np.int64)
    key_valid = rng.random(n) > 0.02
    vals = rng.random(n)
    return keys, key_valid, vals


def bench_tpu(keys, key_valid, vals):
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import entry  # the same fused pipeline

    step, _ = entry()
    jstep = jax.jit(step)
    from spark_rapids_tpu.ops.buckets import bucket_capacity

    n = len(keys)
    cap = bucket_capacity(n)
    kd = jnp.asarray(np.concatenate(
        [keys, np.zeros(cap - n, dtype=np.int64)]))
    kv = jnp.asarray(np.concatenate([key_valid, np.zeros(cap - n, bool)]))
    vd = jnp.asarray(np.concatenate([vals, np.zeros(cap - n)]))
    nr = jnp.int32(n)
    # force with a scalar device_get: under the remote-relay backend
    # block_until_ready can return before execution finishes, which would
    # fake the timing
    for _ in range(WARMUP):
        out = jstep(kd, kv, vd, nr)
        jax.device_get(out[4])
    # steady-state throughput: dispatches pipeline (async), the final
    # device_get forces the LAST step — device execution is in-order, so
    # every earlier step has completed by then. Syncing each iteration
    # would time the tunnel round trip, not the pipeline.
    t0 = time.perf_counter()
    outs = [jstep(kd, kv, vd, nr) for _ in range(ITERS)]
    out = outs[-1]
    jax.device_get(out[4])
    dt = (time.perf_counter() - t0) / ITERS
    return dt, out


def bench_cpu(keys, key_valid, vals):
    import pandas as pd

    df = pd.DataFrame({"k": keys, "valid": key_valid, "v": vals})

    def run():
        f = df[(df["v"] > 0.5) & df["valid"]]
        return f.groupby("k").agg(s=("v", "sum"), c=("v", "count"),
                                  n=("v", "size"))

    run()  # warmup
    t0 = time.perf_counter()
    for _ in range(max(ITERS // 2, 1)):
        out = run()
    dt = (time.perf_counter() - t0) / max(ITERS // 2, 1)
    return dt, out


def _service_warmup(runner, benchmark: str):
    """Warm compile caches through the service warmup ladder before the
    timed run: register_template traces + compiles the query's stage
    programs (persisted via progcache, which IS process-global), then
    replays the bucket-registry rungs so smaller capacity buckets start
    hot too. The throwaway service is discarded — its per-service
    result cache is never consulted by the timed BenchmarkRunner path,
    so the measurement below is a genuine cold-data/hot-code run."""
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.benchmarks.runner import ALL_BENCHMARKS
    from spark_rapids_tpu.service.query_service import QueryService

    runner.ensure_data(benchmark)
    plan = ALL_BENCHMARKS[benchmark](runner.data_dir)
    # single-query run: rungs above the input's own bucket can never be
    # hit, so cap the ladder replay there (BENCH_r08 showed an 11.75 s
    # full-ladder warmup for a 1.6 s q26 run)
    max_rung = _input_rung(plan)
    svc = QueryService({cfg.SERVICE_WARMUP_ENABLED.key: True})
    try:
        report = svc.register_template(plan, name=benchmark,
                                       max_rung=max_rung) or {}
    finally:
        svc.shutdown()
    ladder = report.get("ladder") or {}
    return {"templates": report.get("templates"),
            "ladder_replays": ladder.get("replays"),
            "rungs_skipped": ladder.get("rungs_skipped"),
            "max_rung": max_rung,
            "seconds": report.get("seconds")}


def _input_rung(plan):
    """Ladder bucket of the query's largest input table (from scan-leaf
    row-count estimates), or None when any leaf count is unknown."""
    from spark_rapids_tpu.ops import buckets as _ladder
    from spark_rapids_tpu.plan.nodes import ScanNode

    rows = []
    stack = [getattr(plan, "_plan", plan)]
    while stack:
        node = stack.pop()
        if isinstance(node, ScanNode):
            n = node.source.estimated_row_count()
            if n is None:
                return None
            rows.append(int(n))
        stack.extend(node.children)
    if not rows:
        return None
    return _ladder.bucket_capacity(max(rows))


def bench_full_query(benchmark: str = "tpcxbb_q26", sf: float = 0.1,
                     warmup_service: bool = True, conf=None,
                     iterations: int = 2, data_dir: str = None,
                     skew: float = 0.0):
    """One REAL TPC query end-to-end through the engine (round-5
    verdict: the driver-visible bench must capture a full query whose
    number moves with engine work, not only the q5lite microbench).
    Reports wall, dispatch split, measured on-device seconds, spill
    traffic, and the CPU-oracle comparison — the reference's per-query
    JSON record shape (docs/benchmarks.md:26-169,
    BenchmarkRunner.scala)."""
    from spark_rapids_tpu.benchmarks.runner import BenchmarkRunner

    family = benchmark.split("_")[0]
    # skewed data lands in its own dir: the marker protocol allows one
    # dataset per dir, and a skewed run must not poison uniform reruns
    default_dir = os.path.join(
        "/tmp", f"srt_bench_{family}" + (f"_skew{skew}" if skew else ""))
    r = BenchmarkRunner(data_dir or default_dir, sf, conf=conf,
                        skew=skew)
    warmed = None
    if warmup_service:
        try:
            warmed = _service_warmup(r, benchmark)
        except Exception as e:  # advisory: a warmup fault must not
            warmed = {"error": str(e)[:120]}  # sink the measurement
    res = r.run(benchmark, iterations=iterations, warmup=1,
                compare=True)
    wall = res["min_time_sec"]
    dt = res.get("dispatch_telemetry", {})
    devt = res.get("device_timing", {})
    cmp_ = res.get("compare", {})
    cpu_s = cmp_.get("cpu_time_sec", 0.0)
    mem = res.get("memory", {})
    return {
        "benchmark": benchmark,
        "sf": sf,
        # backend identity: which device actually produced these
        # numbers (platform, kind, count) plus the measured per-dispatch
        # rtt floor — a local-CPU record and a remote-TPU record must be
        # distinguishable from the JSON alone
        "backend": res.get("env"),
        "wall_s": round(wall, 3),
        "dispatch_count": dt.get("dispatch_count"),
        # stage-cut attribution: measured round trips per pipeline
        # stage (the whole-plan coalescing target is ~1 per stage)
        "per_stage_dispatch": dt.get("per_stage"),
        # the named complement: WHICH programs each stage launched, so
        # a regression in fusion shows up as a program-name diff rather
        # than a bare count bump (round-7)
        "per_stage_programs": dt.get("per_stage_programs"),
        # measured on-device seconds per (stage, program) from the
        # serialized timing pass — the stage breakdown in TIME, not
        # just round trips (a stage can be 1 dispatch and 4 seconds)
        "per_stage_device_s": devt.get("per_stage_programs_device_s"),
        # mesh-requested shuffles that stayed on the host/TCP path,
        # with the spmd gate's reason (empty = all folded in-program)
        "shuffle_fallbacks": dt.get("shuffle_fallbacks"),
        # every AQE replan the run made (skew splits/salting, strategy
        # switches, re-bucketing) with counts; empty = static plan ran
        "replan_events": res.get("replan_events"),
        "io_scan": res.get("io_scan"),
        # generator provenance: a skewed record names its distribution
        # so the JSON alone says what data produced these numbers
        "skew_params": {
            "skew": skew,
            "distribution": f"zipf(s=2, ranks={_skew_ranks()})",
            "hot_key_fraction": skew,
            "table": "lineitem", "column": "l_orderkey",
        } if skew else None,
        "rtt_share": round(
            min(dt.get("est_dispatch_overhead_s", 0.0) / wall, 1.0), 3)
        if wall else None,
        "on_device_s_measured": devt.get("on_device_s"),
        "cpu_oracle_s": round(cpu_s, 3),
        "vs_cpu_oracle": round(cpu_s / wall, 3) if wall else None,
        "matches_cpu": cmp_.get("matches_cpu"),
        # spill-tier traffic over the run (deltas) + the enforced
        # budget: nonzero spilled_* here is the proof an sf>=1 run
        # exercised the out-of-core chain on real query data
        "spilled_device_bytes": mem.get("spilled_device_bytes"),
        "spilled_host_bytes": mem.get("spilled_host_bytes"),
        "device_budget": mem.get("device_budget"),
        "warmup": warmed,
    }


def _skew_ranks() -> int:
    from spark_rapids_tpu.benchmarks import datagen

    return datagen.SKEW_RANKS


def _scale_main():
    """``python bench.py --query tpch_q1 --sf 1 [--device-budget N]``:
    one full query at scale, printed as a single JSON line. This is the
    sf >= 1 measurement path (CPU-oracle crossover, spill engagement);
    the flagless invocation keeps the driver's q5lite + q26 round
    unchanged. ``--device-budget`` bounds the spill catalog (bytes) so
    a large-sf run models a device whose HBM the working set exceeds —
    the recorded JSON carries the budget so the spill counters are
    interpretable."""
    from spark_rapids_tpu.utils import dispatch as disp

    disp.install()
    seed_compile_cache()
    from spark_rapids_tpu.utils import progcache

    progcache.install()

    def arg(name, default=None, cast=str):
        if name in sys.argv:
            return cast(sys.argv[sys.argv.index(name) + 1])
        return default

    benchmark = arg("--query")
    sf = arg("--sf", 1.0, float)
    budget = arg("--device-budget", 0, int)
    iters = arg("--iterations", 2, int)
    skew = arg("--skew", 0.0, float)
    kernels = "--kernels" in sys.argv

    def _conf_value(v: str):
        if v.lower() in ("true", "false"):
            return v.lower() == "true"
        for cast in (int, float):
            try:
                return cast(v)
            except ValueError:
                pass
        return v

    # repeatable --conf key=value passthrough (session knobs only —
    # e.g. forcing adaptive skew thresholds for a skewed-join record)
    overrides = {}
    for i, a in enumerate(sys.argv):
        if a == "--conf" and i + 1 < len(sys.argv):
            k, _, v = sys.argv[i + 1].partition("=")
            overrides[k] = _conf_value(v)
    conf = None
    if budget or kernels or overrides:
        from spark_rapids_tpu import config as cfg
        from spark_rapids_tpu.config import RapidsConf
        from spark_rapids_tpu.runtime import device as rt

        conf_d = dict(overrides)
        if budget:
            conf_d[cfg.DEVICE_BUDGET.key] = budget
        if kernels:
            # native Pallas kernel gates are process-wide (same
            # contract as memory/retry): initialize applies them
            conf_d[cfg.NATIVE_KERNELS_ENABLED.key] = True
        conf = RapidsConf(conf_d)
        if budget or kernels:
            rt.initialize(conf)  # budgeted spill catalog + kernel gates
    full = bench_full_query(benchmark, sf=sf,
                            warmup_service="--no-warmup" not in sys.argv,
                            conf=conf, iterations=iters,
                            data_dir=arg("--data-dir"), skew=skew)
    refresh_cache_seed()
    print(json.dumps({"metric": "full_query_scale", "full_query": full}))


def main():
    # telemetry wraps jax.jit; must precede every compute-module import
    from spark_rapids_tpu.utils import dispatch as disp

    disp.install()
    seed_compile_cache()
    # persist every executable compiled below (adopts the platform-
    # suffixed cache dir the package __init__ configured; the tracked
    # seed dir feeds it at startup) — a repeated bench run starts hot
    # even in a fresh process
    from spark_rapids_tpu.utils import progcache

    progcache.install()
    keys, key_valid, vals = gen_data()
    tpu_dt, tpu_out = bench_tpu(keys, key_valid, vals)
    refresh_cache_seed()
    cpu_dt, cpu_out = bench_cpu(keys, key_valid, vals)
    full = None
    # --warmup is default-on (PR 7 ladder: first real query starts
    # hot); --no-warmup opts out for cold-compile measurements
    warmup_service = "--no-warmup" not in sys.argv
    try:
        full = bench_full_query(warmup_service=warmup_service)
    except Exception as e:  # the headline line must still print
        full = {"error": f"{type(e).__name__}: {e}"[:300]}

    # cross-check: group count and total sum must agree
    import jax

    ng = int(jax.device_get(tpu_out[4]))
    tpu_sum = float(np.asarray(jax.device_get(tpu_out[1]))[:ng].sum())
    cpu_sum = float(cpu_out["s"].sum())
    assert ng == len(cpu_out), (ng, len(cpu_out))
    assert abs(tpu_sum - cpu_sum) / max(abs(cpu_sum), 1) < 1e-9

    rows_per_sec = N_ROWS / tpu_dt
    speedup = cpu_dt / tpu_dt
    print(json.dumps({
        "metric": "q5lite_filter_groupby_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(speedup, 3),
        "full_query": full,
    }))


if __name__ == "__main__":
    if "--query" in sys.argv:
        _scale_main()
    else:
        main()
